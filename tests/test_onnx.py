"""ONNX export/import round-trips.

Reference test model: tests/python-pytest/onnx/test_models.py — export a
model, re-import, compare logits exactly (same params round-tripped
through the ONNX file).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
import mxnet_tpu.symbol as sym
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.gluon.model_zoo import vision

rs = onp.random.RandomState(7)


def _roundtrip_block(net, shape, tmp_path, rtol=1e-4, atol=1e-4):
    net.initialize(mx.init.Xavier())
    x = nd.array(rs.rand(*shape).astype("f"))
    ref = net(x)
    prefix = str(tmp_path / "m")
    net.export(prefix, epoch=0)
    onnx_file = onnx_mxnet.export_model(
        prefix + "-symbol.json", prefix + "-0000.params", shape,
        onnx_file_path=str(tmp_path / "m.onnx"))
    assert os.path.getsize(onnx_file) > 0
    s, args, aux = onnx_mxnet.import_model(onnx_file)
    feed = {"data": x}
    feed.update(args)
    feed.update(aux)
    ex = s.bind(mx.cpu(), feed)
    (out,) = ex.forward()
    onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                rtol=rtol, atol=atol)
    return onnx_file


@pytest.mark.parametrize("ctor,shape", [
    (vision.resnet18_v1, (1, 3, 224, 224)),
    (vision.resnet18_v2, (1, 3, 224, 224)),
    (vision.mobilenet_v2_0_25, (1, 3, 224, 224)),
    (vision.mobilenet0_25, (1, 3, 224, 224)),
    (vision.squeezenet1_0, (1, 3, 224, 224)),
    # the three heaviest zoo members (~90s of tier-1 on one core) ride
    # the slow lane; their exporter surface (conv+BN stacks, dense
    # concat blocks) is covered by the resnet/mobilenet members above
    pytest.param(vision.densenet121, (1, 3, 224, 224),
                 marks=pytest.mark.slow),
    pytest.param(vision.vgg11_bn, (1, 3, 224, 224),
                 marks=pytest.mark.slow),
    (vision.alexnet, (1, 3, 224, 224)),
    pytest.param(vision.inception_v3, (1, 3, 299, 299),
                 marks=pytest.mark.slow),
])
def test_zoo_family_onnx_roundtrip(ctor, shape, tmp_path):
    _roundtrip_block(ctor(classes=10), shape, tmp_path)


def test_onnx_metadata(tmp_path):
    net = vision.squeezenet1_0(classes=10)
    f = _roundtrip_block(net, (2, 3, 224, 224), tmp_path)
    meta = onnx_mxnet.get_model_metadata(f)
    assert meta["input_tensor_data"] == [("data", (2, 3, 224, 224))]
    assert len(meta["output_tensor_data"]) == 1


def test_import_to_gluon(tmp_path):
    net = vision.mobilenet0_25(classes=10)
    net.initialize(mx.init.Xavier())
    x = nd.array(rs.rand(1, 3, 224, 224).astype("f"))
    ref = net(x)
    prefix = str(tmp_path / "g")
    net.export(prefix)
    f = onnx_mxnet.export_model(
        prefix + "-symbol.json", prefix + "-0000.params",
        (1, 3, 224, 224), onnx_file_path=str(tmp_path / "g.onnx"))
    net2 = onnx_mxnet.import_to_gluon(f)
    out = net2(x)
    onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                rtol=1e-4, atol=1e-4)


def test_onnx_mlp_ops(tmp_path):
    """Dense/softmax/dropout/reshape path without conv."""
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh"), nn.Dropout(0.2), nn.Dense(4))
    _roundtrip_block(net, (3, 8), tmp_path)


def test_onnx_reduce_gelu_group(tmp_path):
    """Reduce ops (opset-13 attr/input forms), gelu decomposition, and
    multi-output Group export."""
    a = sym.Variable("data")
    m = sym.mean(a, axis=1, keepdims=True)
    s = sym.sum(a, axis=0)
    g = sym.leaky_relu(a, act_type="gelu")
    out = sym.Group([m, s, g])
    A = rs.rand(3, 5).astype("f")
    f = onnx_mxnet.export_model(out, {}, (3, 5),
                                onnx_file_path=str(tmp_path / "r.onnx"))
    s2, args, aux = onnx_mxnet.import_model(f)
    ex = s2.bind(mx.cpu(), {"data": nd.array(A)})
    rm, rsum, rg = ex.forward()
    onp.testing.assert_allclose(rm.asnumpy(), A.mean(1, keepdims=True),
                                rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(rsum.asnumpy(), A.sum(0), rtol=1e-5,
                                atol=1e-6)
    import math

    erf = onp.array([[math.erf(v / 2 ** 0.5) for v in row] for row in A],
                    "f")
    onp.testing.assert_allclose(rg.asnumpy(), 0.5 * A * (1 + erf),
                                rtol=1e-4, atol=1e-5)


def test_onnx_symbol_level_ops(tmp_path):
    """Hand-built symbol covering scalar/broadcast/reduce translations."""
    a = sym.Variable("data")
    out = sym.broadcast_add(sym.transpose(a * 2.0 + 1.0), a * 1.0)
    out = sym.reshape(out, shape=(-1,))
    A = rs.rand(4, 4).astype("f")
    ref = (A.T * 2 + 1 + A).reshape(-1)
    f = onnx_mxnet.export_model(out, {}, (4, 4),
                                onnx_file_path=str(tmp_path / "s.onnx"))
    s, args, aux = onnx_mxnet.import_model(f)
    ex = s.bind(mx.cpu(), {"data": nd.array(A)})
    (res,) = ex.forward()
    onp.testing.assert_allclose(res.asnumpy(), ref, rtol=1e-5, atol=1e-5)


def _rt_sym(out_sym, feed, tmp_path, fname, in_shapes, rtol=1e-5,
            atol=1e-5, extra_feed=None):
    """Export a hand-built symbol, re-import, compare eval outputs."""
    ref = out_sym.eval_with(dict(feed))
    f = onnx_mxnet.export_model(out_sym, dict(extra_feed or {}), in_shapes,
                                onnx_file_path=str(tmp_path / fname))
    s, args, aux = onnx_mxnet.import_model(f)
    feed2 = dict(feed)
    feed2.update(args)
    feed2.update(aux)
    got = s.eval_with(feed2)
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    gots = got if isinstance(got, (list, tuple)) else [got]
    for r, g in zip(refs, gots):
        onp.testing.assert_allclose(g.asnumpy(), r.asnumpy(),
                                    rtol=rtol, atol=atol)


def test_onnx_r5_indexing_ops(tmp_path):
    """slice/slice_axis/split/take/tile/broadcast_to/stack round-trips."""
    a = sym.Variable("data")
    A = rs.rand(4, 6).astype("f")
    _rt_sym(sym.slice(a, begin=(1, 0), end=(3, 4)), {"data": nd.array(A)},
            tmp_path, "sl.onnx", (4, 6))
    _rt_sym(sym.slice_axis(a, axis=1, begin=2, end=5),
            {"data": nd.array(A)}, tmp_path, "sa.onnx", (4, 6))
    _rt_sym(sym.tile(a, reps=(2, 1)), {"data": nd.array(A)}, tmp_path,
            "ti.onnx", (4, 6))
    parts = sym.split(a, num_outputs=2, axis=1)
    _rt_sym(sym.Group([parts[0], parts[1]]), {"data": nd.array(A)},
            tmp_path, "sp.onnx", (4, 6))
    idx = sym.Variable("idx")
    _rt_sym(sym.take(a, idx, axis=0),
            {"data": nd.array(A), "idx": nd.array([0., 2., 1.])},
            tmp_path, "tk.onnx", {"data": (4, 6), "idx": (3,)})
    b = sym.Variable("b")
    B = rs.rand(1, 6).astype("f")
    _rt_sym(sym.broadcast_to(b, shape=(4, 6)), {"b": nd.array(B)},
            tmp_path, "bt.onnx", {"b": (1, 6)})
    _rt_sym(sym.stack(a, a * 2.0, axis=0), {"data": nd.array(A)},
            tmp_path, "st.onnx", (4, 6))


def test_onnx_r5_compare_where_onehot(tmp_path):
    a = sym.Variable("data")
    b = sym.Variable("b")
    A = rs.rand(3, 4).astype("f")
    B = rs.rand(3, 4).astype("f")
    feed = {"data": nd.array(A), "b": nd.array(B)}
    shapes = {"data": (3, 4), "b": (3, 4)}
    _rt_sym(sym.broadcast_greater(a, b), feed, tmp_path, "gt.onnx", shapes)
    _rt_sym(sym.broadcast_not_equal(a, b), feed, tmp_path, "ne.onnx",
            shapes)
    _rt_sym(sym.where(sym.broadcast_greater(a, b), a, b), feed, tmp_path,
            "wh.onnx", shapes)
    lbl = sym.Variable("lbl")
    _rt_sym(sym.one_hot(lbl, depth=5),
            {"lbl": nd.array([0., 3., 2.])}, tmp_path, "oh.onnx",
            {"lbl": (3,)})


def test_onnx_r5_math_norm_argmax(tmp_path):
    a = sym.Variable("data")
    A = (rs.rand(3, 5).astype("f") - 0.3)
    feed = {"data": nd.array(A)}
    for op in ("sin", "cos", "round", "sign", "reciprocal", "arctan"):
        _rt_sym(getattr(sym, op)(a), feed, tmp_path, f"{op}.onnx", (3, 5),
                rtol=1e-4, atol=1e-5)
    _rt_sym(sym.norm(a, ord=2, axis=1), feed, tmp_path, "l2.onnx", (3, 5))
    _rt_sym(sym.argmax(a, axis=1), feed, tmp_path, "am.onnx", (3, 5))
    _rt_sym(sym.cast(a, dtype="int32"), feed, tmp_path, "ct.onnx", (3, 5))
    vals_idx = sym.topk(a, k=2, axis=1, ret_typ="both")
    _rt_sym(sym.Group([vals_idx[0], vals_idx[1]]), feed, tmp_path,
            "tkk.onnx", (3, 5))


def test_onnx_r5_norm_layers(tmp_path):
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(12), nn.LayerNorm())
    _roundtrip_block(net, (2, 8), tmp_path)
    net2 = nn.HybridSequential()
    net2.add(nn.Conv2D(4, 3, padding=1), nn.InstanceNorm(),
             nn.Activation("relu"))
    _roundtrip_block(net2, (2, 3, 8, 8), tmp_path)


def test_onnx_r5_embedding_gather(tmp_path):
    w = sym.Variable("w")
    idx = sym.Variable("data")
    emb = sym.Embedding(idx, w, input_dim=10, output_dim=4)
    W = rs.rand(10, 4).astype("f")
    _rt_sym(emb, {"data": nd.array([1., 4., 7.]), "w": nd.array(W)},
            tmp_path, "em.onnx", {"data": (3,)},
            extra_feed={"w": nd.array(W)})
