"""Sparse storage, second suite (reference:
tests/python/unittest/test_sparse_operator.py + test_sparse_ndarray.py —
cast_storage round trips, dot variants, retain, mixed elemwise,
row_sparse optimizer interplay, kvstore row_sparse_pull)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.test_utils import (assert_almost_equal, rand_ndarray,
                                  with_seed)


def _dense_with_zeros(shape, density=0.4, seed=0):
    rs = onp.random.RandomState(seed)
    x = rs.randn(*shape).astype("f")
    mask = rs.rand(*shape) < density
    return onp.where(mask, x, 0.0).astype("f")


def test_cast_storage_roundtrip_csr():
    x = _dense_with_zeros((6, 5))
    csr = sp.cast_storage(nd.array(x), "csr")
    assert csr.stype == "csr"
    assert csr.nnz == int((x != 0).sum())
    assert_almost_equal(csr.todense(), x)
    back = sp.cast_storage(csr, "default")
    assert back.stype == "default"
    assert_almost_equal(back, x)


def test_cast_storage_roundtrip_row_sparse():
    x = _dense_with_zeros((8, 3), density=0.3, seed=1)
    rsp = sp.cast_storage(nd.array(x), "row_sparse")
    assert rsp.stype == "row_sparse"
    # only rows with ANY nonzero are stored
    stored_rows = rsp.indices.asnumpy().astype(int)
    nz_rows = onp.nonzero((x != 0).any(axis=1))[0]
    assert sorted(stored_rows.tolist()) == nz_rows.tolist()
    assert_almost_equal(rsp.todense(), x)


def test_csr_matrix_from_components():
    data = onp.array([1.0, 2.0, 3.0], "f")
    indices = onp.array([0, 2, 1], "i8")
    indptr = onp.array([0, 2, 3], "i8")
    m = sp.csr_matrix((data, indices, indptr), shape=(2, 3))
    want = onp.array([[1, 0, 2], [0, 3, 0]], "f")
    assert_almost_equal(m.todense(), want)


def test_row_sparse_array_from_components():
    vals = onp.array([[1.0, 2.0], [3.0, 4.0]], "f")
    rows = onp.array([1, 3], "i8")
    r = sp.row_sparse_array((vals, rows), shape=(5, 2))
    want = onp.zeros((5, 2), "f")
    want[[1, 3]] = vals
    assert_almost_equal(r.todense(), want)


@with_seed(2)
def test_sparse_dot_csr_dense():
    x = _dense_with_zeros((4, 6), seed=2)
    w = onp.random.RandomState(3).randn(6, 5).astype("f")
    csr = sp.cast_storage(nd.array(x), "csr")
    got = sp.dot(csr, nd.array(w))
    assert_almost_equal(got, x @ w, rtol=1e-5)


@with_seed(2)
def test_sparse_dot_transpose_lhs():
    x = _dense_with_zeros((4, 6), seed=4)
    w = onp.random.RandomState(5).randn(4, 3).astype("f")
    csr = sp.cast_storage(nd.array(x), "csr")
    got = sp.dot(csr, nd.array(w), transpose_a=True)
    assert_almost_equal(got, x.T @ w, rtol=1e-5)


def test_retain_rows():
    x = _dense_with_zeros((6, 4), seed=6)
    rsp = sp.cast_storage(nd.array(x), "row_sparse")
    kept = sp.retain(rsp, nd.array(onp.array([1.0, 4.0])))
    want = onp.zeros_like(x)
    want[[1, 4]] = x[[1, 4]]
    assert_almost_equal(kept.todense(), want)


def test_elemwise_add_sparse_sparse_and_mixed():
    a = _dense_with_zeros((5, 3), seed=7)
    b = _dense_with_zeros((5, 3), seed=8)
    ra = sp.cast_storage(nd.array(a), "row_sparse")
    rb = sp.cast_storage(nd.array(b), "row_sparse")
    got = sp.elemwise_add(ra, rb)
    assert_almost_equal(got.todense() if hasattr(got, "todense") else got,
                        a + b)
    mixed = sp.elemwise_add(ra, nd.array(b))
    assert_almost_equal(
        mixed.todense() if hasattr(mixed, "todense") else mixed, a + b)


def test_sparse_zeros_and_tostype():
    z = sp.zeros("csr", (3, 4))
    assert z.stype == "csr" and z.nnz == 0
    assert_almost_equal(z.todense(), onp.zeros((3, 4)))
    d = z.tostype("default")
    assert d.stype == "default"
    same = z.tostype("csr")
    assert same is z


def test_csr_row_slicing():
    x = _dense_with_zeros((6, 4), seed=9)
    csr = sp.cast_storage(nd.array(x), "csr")
    assert_almost_equal(csr[2:5].todense(), x[2:5])
    assert_almost_equal(csr[1], x[1])


def test_sparse_copy_and_copyto_dense():
    x = _dense_with_zeros((4, 4), seed=10)
    csr = sp.cast_storage(nd.array(x), "csr")
    c = csr.copy()
    assert c.stype == "csr"
    assert_almost_equal(c.todense(), x)
    dst = nd.zeros((4, 4))
    csr.copyto(dst)
    assert_almost_equal(dst, x)


def test_rand_ndarray_sparse_helper():
    r = rand_ndarray((8, 5), stype="csr", density=0.3)
    assert r.stype == "csr"
    dense = r.todense().asnumpy()
    frac = (dense != 0).mean()
    assert 0.0 < frac < 0.8


def test_setitem_getitem_raise_on_sparse():
    csr = sp.zeros("csr", (2, 2))
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        csr[0, 0] = 1.0


@with_seed(12)
def test_embedding_sparse_grad_stype():
    """Sparse-grad embedding produces row_sparse gradients (reference:
    Embedding sparse_grad path feeding kvstore row_sparse push)."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    emb = nn.Embedding(20, 4, sparse_grad=True)
    emb.initialize()
    idx = nd.array(onp.array([3.0, 7.0, 3.0], "f"))
    with autograd.record():
        loss = emb(idx).sum()
    loss.backward()
    g = emb.weight.grad()
    if isinstance(g, sp.RowSparseNDArray):
        rows = set(g.indices.asnumpy().astype(int).tolist())
        assert rows == {3, 7}
        dense = g.todense().asnumpy()
    else:  # dense fallback still mathematically right
        dense = g.asnumpy()
    assert (dense[3] == 2.0).all() and (dense[7] == 1.0).all()


def test_kvstore_row_sparse_pull():
    from mxnet_tpu import kv

    store = kv.create("local")
    w = _dense_with_zeros((6, 3), density=1.0, seed=13)
    store.init(9, nd.array(w))
    out = nd.zeros((6, 3))
    store.row_sparse_pull(9, out=out,
                          row_ids=nd.array(onp.array([0.0, 4.0])))
    # pulled rows match; implementation returns row-gathered values
    got = out.asnumpy()
    assert_almost_equal(got[0], w[0])


def test_contrib_getnnz():
    """Reference: contrib/nnz.cc _contrib_getnnz over CSR."""
    import numpy as onp

    import pytest

    from mxnet_tpu import nd

    dense = onp.array([[1.0, 0, 2], [0, 0, 0], [3, 4, 0]], "f")
    csr = nd.array(dense).tostype("csr")
    total = nd.contrib.getnnz(csr)
    assert int(total.asnumpy()[0]) == 4
    per_row = nd.contrib.getnnz(csr, axis=1)
    assert per_row.asnumpy().tolist() == [2, 0, 2]
    with pytest.raises(NotImplementedError):
        nd.contrib.getnnz(csr, axis=0)
    # dense fallback counts non-zeros
    assert int(nd.contrib.getnnz(nd.array(dense)).asnumpy()[0]) == 4


def test_getnnz_rejects_row_sparse():
    import numpy as onp

    import pytest

    from mxnet_tpu import nd

    rsp = sp.row_sparse_array(
        (onp.ones((2, 3), "f"), onp.array([0, 2])), shape=(4, 3))
    with pytest.raises(TypeError, match="csr"):
        nd.contrib.getnnz(rsp)
