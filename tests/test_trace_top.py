"""tools/trace_top over a real jax.profiler capture (reference analog:
profiler aggregate-stats dump)."""
import glob
import os

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_trace_top_summarizes_real_capture(tmp_path, capsys):
    import jax

    logdir = str(tmp_path / "prof")
    a = nd.array(onp.random.RandomState(0).rand(64, 64).astype("f"))
    with jax.profiler.trace(logdir):
        for _ in range(3):
            a = nd.dot(a, a)
            a = nd.relu(a)
        a.wait_to_read()
    assert glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                     recursive=True)
    from mxnet_tpu.tools import trace_top

    rc = trace_top.main([logdir, "-n", "10"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "self_ms" in out and "device events" in out
    # the dot-relu loop must surface some compute row
    assert any(tok in out for tok in ("dot", "fusion", "jit", "relu",
                                      "convert", "eigen", "matmul",
                                      "gemm", "Xla", "xla"))
    # full-name mode runs too
    assert trace_top.main([logdir, "--by", "name", "-n", "5"]) == 0
