"""Sharded distributed checkpointing (parallel/checkpoint.py over
orbax): resume-exactness and mesh-layout resharding on restore
(SURVEY §5.4 checkpoint/resume at multi-chip scale).
"""
import numpy as onp
import pytest

pytest.importorskip("orbax.checkpoint")

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel


def _trainer(mesh, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="adam",
        optimizer_params={"learning_rate": 0.01}, mesh=mesh)


def _batch(rng, b=8):
    return (nd.array(rng.rand(b, 8).astype("f")),
            nd.array(rng.randint(0, 4, b).astype("f")))


def test_trainer_checkpoint_resume_exact(tmp_path):
    mesh = parallel.make_mesh({"dp": 4})
    rng = onp.random.RandomState(0)
    t1 = _trainer(mesh)
    x, y = _batch(rng)
    for _ in range(3):
        t1.step(x, y)
    parallel.save_trainer(str(tmp_path / "ck"), t1)
    # continue the original for 2 more steps
    losses_cont = [float(t1.step(x, y).asscalar()) for _ in range(2)]
    # a FRESH trainer (different init seed) restored from the checkpoint
    # must reproduce the same continuation exactly — params, adam
    # moments, RNG key and step counter all came back
    t2 = _trainer(mesh, seed=99)
    t2.step(x, y)  # build
    parallel.load_trainer(str(tmp_path / "ck"), t2)
    losses_resume = [float(t2.step(x, y).asscalar()) for _ in range(2)]
    onp.testing.assert_allclose(losses_resume, losses_cont, rtol=1e-5)


def test_sharded_save_restore_reshards(tmp_path):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh4 = parallel.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    arr = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
        NamedSharding(mesh4, P("dp")))
    parallel.save_sharded(str(tmp_path / "arr"), {"w": arr})
    # restore onto a DIFFERENT layout: 8-way mesh
    mesh8 = parallel.make_mesh({"dp": 8})
    tgt = NamedSharding(mesh8, P("dp"))
    back = parallel.load_sharded(str(tmp_path / "arr"),
                                 shardings={"w": tgt})
    onp.testing.assert_array_equal(onp.asarray(back["w"]),
                                   onp.arange(32).reshape(8, 4))
    assert back["w"].sharding.mesh.shape["dp"] == 8


def test_save_overwrite_and_tuple_trees(tmp_path):
    mesh = parallel.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = NamedSharding(mesh, P())
    tree = {"pair": (jax.device_put(jnp.ones(2), s),
                     jax.device_put(jnp.zeros(2), s))}
    p = str(tmp_path / "fixed")
    parallel.save_sharded(p, tree)
    parallel.save_sharded(p, tree)  # periodic save to a fixed path
    back = parallel.load_sharded(p, shardings={"pair": (s, s)})
    onp.testing.assert_array_equal(onp.asarray(back["pair"][0]),
                                   [1, 1])


def test_load_sharded_like(tmp_path):
    mesh = parallel.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    from jax.sharding import NamedSharding, PartitionSpec as P

    a = jax.device_put(jnp.ones((4, 2)), NamedSharding(mesh, P("dp")))
    parallel.save_sharded(str(tmp_path / "t"), {"a": a})
    out = parallel.load_sharded(str(tmp_path / "t"), like={"a": a})
    assert out["a"].sharding == a.sharding
    onp.testing.assert_array_equal(onp.asarray(out["a"]), onp.ones((4, 2)))
