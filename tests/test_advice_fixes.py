"""Regression tests for round-1 advisor findings (ADVICE.md r1).

Each test pins one of the five fixes: engine callback GC, flash-attention
causal shape guard, NaiveEngine version bump on error, persistent
calibration RNG, writable-recordio pickle guard.
"""
import pickle

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, engine, recordio
from mxnet_tpu.gluon import nn


def test_engine_callback_gc_after_wait_all():
    try:
        eng = engine.Engine(nthreads=2)
    except RuntimeError:
        pytest.skip("native engine unavailable")
    out = []
    for i in range(64):
        v = eng.new_variable()
        eng.push(lambda i=i: out.append(i), mutable_vars=(v,))
    eng.wait_all()
    assert len(out) == 64
    # full barrier -> every trampoline has returned; keepalives dropped
    assert eng.num_live_callbacks() == 0
    # poison survives GC: error pushed after the barrier still re-raises
    v = eng.new_variable()

    def boom():
        raise ValueError("poison")

    eng.push(boom, mutable_vars=(v,))
    with pytest.raises(ValueError, match="poison"):
        eng.wait_for_var(v)
    eng.wait_all()
    assert eng.num_live_callbacks() == 0


def test_flash_attention_causal_sq_gt_sk_raises():
    from mxnet_tpu.ops.flash_attention import flash_attention
    import jax.numpy as jnp

    q = jnp.zeros((1, 2, 8, 4))
    kv = jnp.zeros((1, 2, 4, 4))
    with pytest.raises(ValueError, match="S_q <= S_k"):
        flash_attention(q, kv, kv, causal=True)
    # non-causal cross-attention with S_q > S_k stays legal
    o = flash_attention(q, kv, kv, causal=False)
    assert o.shape == (1, 2, 8, 4)


def test_naive_engine_version_bump_on_error():
    eng = engine.NaiveEngine()
    v = eng.new_variable()
    eng.push(lambda: None, mutable_vars=(v,))
    assert eng.var_version(v) == 1

    def boom():
        raise RuntimeError("x")

    eng.push(boom, mutable_vars=(v,))
    # native Complete() bumps the version even on failure — match it
    assert eng.var_version(v) == 2
    with pytest.raises(RuntimeError):
        eng.wait_for_var(v)


def test_quant_entropy_reservoir_persistent_rng(monkeypatch):
    from mxnet_tpu.contrib import quantization as qz

    calls = []
    real = onp.random.RandomState

    class Recording(real):
        def __init__(self, *a, **kw):
            calls.append(a)
            super().__init__(*a, **kw)

    monkeypatch.setattr(onp.random, "RandomState", Recording)
    net = nn.Dense(4)
    net.initialize()
    rs = real(7)
    # 3 equal-size batches each larger than the 16384-sample reservoir cap
    batches = [nd.array(rs.randn(64, 600).astype("float32"))
               for _ in range(3)]
    qz.quantize_net(net, calib_data=batches, calib_mode="entropy")
    # one persistent RNG per quantize_net call, not one per batch
    assert len(calls) <= 1


def test_writable_recordio_pickle_raises(tmp_path):
    w = recordio.MXRecordIO(str(tmp_path / "a.rec"), "w")
    w.write(b"hello")
    with pytest.raises(RuntimeError, match="writable"):
        pickle.dumps(w)
    w.close()
    r = recordio.MXRecordIO(str(tmp_path / "a.rec"), "r")
    r2 = pickle.loads(pickle.dumps(r))  # readable pickling still works
    assert r2.read() == b"hello"


# ---- round-3 advisor findings -------------------------------------------

def test_c_predictor_loads_bn_aux_states():
    """CPredictor must load aux: prefixed params (BN moving stats) — a
    predictor serving bind-time defaults (mean 0 / var 1) is silently
    wrong for any exported model with BatchNorm (ADVICE r3 high)."""
    from mxnet_tpu import sym
    from mxnet_tpu.c_bridge import CPredictor

    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn0", fix_gamma=False)
    rng = onp.random.RandomState(3)
    gamma = rng.rand(6).astype("f") + 0.5
    beta = rng.randn(6).astype("f")
    mmean = rng.randn(6).astype("f") * 2      # far from default 0
    mvar = rng.rand(6).astype("f") * 5 + 1    # far from default 1
    params = {"arg:bn0_gamma": nd.array(gamma),
              "arg:bn0_beta": nd.array(beta),
              "aux:bn0_moving_mean": nd.array(mmean),
              "aux:bn0_moving_var": nd.array(mvar)}
    buf = nd.save_tobuffer(params) if hasattr(nd, "save_tobuffer") else None
    if buf is None:
        import tempfile, os as _os
        fd, path = tempfile.mkstemp(suffix=".params")
        _os.close(fd)
        nd.save(path, params)
        with open(path, "rb") as f:
            buf = f.read()
        _os.unlink(path)
    pred = CPredictor(bn.tojson(), buf, input_shapes={"data": (2, 6)})
    x = rng.randn(2, 6).astype("f")
    pred.set_input("data", x.tobytes())
    pred.forward()
    got = onp.frombuffer(pred.output_bytes(0), "f").reshape(2, 6)
    want = gamma * (x - mmean) / onp.sqrt(mvar + 1e-3) + beta
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # reshape keeps the loaded aux states, not bind-time defaults
    pred.reshape({"data": (4, 6)})
    x2 = rng.randn(4, 6).astype("f")
    pred.set_input("data", x2.tobytes())
    pred.forward()
    got2 = onp.frombuffer(pred.output_bytes(0), "f").reshape(4, 6)
    want2 = gamma * (x2 - mmean) / onp.sqrt(mvar + 1e-3) + beta
    onp.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-5)


def test_c_predictor_output_shape_before_forward():
    """Output shapes come from bind-time inference — available right
    after create, like the reference MXPredGetOutputShape (ADVICE r3)."""
    from mxnet_tpu import sym
    from mxnet_tpu.c_bridge import CPredictor

    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=7)
    pred = CPredictor(fc.tojson(), b"", input_shapes={"data": (5, 3)})
    assert pred.num_outputs() == 1
    assert pred.output_shape(0) == (5, 7)  # no forward() yet


def test_dgl_edge_ids_exact_past_2_24():
    """64-bit edge ids survive the op outputs exactly: float32 rounds
    16777217 to 16777216 (ADVICE r3 medium)."""
    from mxnet_tpu.ndarray import sparse as sp
    from mxnet_tpu.ndarray.contrib import (edge_id, dgl_subgraph,
                                           dgl_graph_compact)

    big = float(2**24 + 1)
    data = onp.asarray([big, big + 2, big + 4, big + 6], onp.float64)
    indices = onp.asarray([1, 0, 2, 1], onp.int64)
    indptr = onp.asarray([0, 1, 3, 4], onp.int64)
    # the public id-exact construction path (the plain constructor's
    # device payload would round float64 through float32)
    g = sp.CSRNDArray.from_host(data, indices, indptr, (3, 3))
    out = edge_id(g, nd.array([0, 1]), nd.array([1, 2])).asnumpy()
    assert out.dtype == onp.float64
    assert out[0] == big          # exact, not 2^24
    assert out[1] == big + 4
    # densify stays exact too (inherited jnp todense would truncate)
    dense = g.asnumpy()
    assert dense.dtype == onp.float64
    assert dense[0, 1] == big and dense[2, 1] == big + 6
    subs = dgl_subgraph(g, nd.array([0, 1, 2]), return_mapping=True)
    mapping = subs[1]
    vals = mapping.data.asnumpy()
    assert vals.dtype == onp.float64
    # mapping holds parent edge id + 1 — positions, small; but its
    # payload container must be 64-bit safe end to end
    assert mapping._indices.dtype == onp.int64
    assert mapping.asnumpy().dtype == onp.float64
    # copy()/slice keep the host class and exact payload
    cp = g.copy()
    assert cp.asnumpy()[0, 1] == big
    row01 = g.slice(0, 2)
    assert row01.data.asnumpy()[0] == big
    # id arrays stay mutable (numpy payload, not jax .at)
    ids = edge_id(g, nd.array([0, 1]), nd.array([1, 2]))
    ids[0] = -1.0
    assert ids.asnumpy()[0] == -1 and ids.asnumpy()[1] == big + 4
    # compact preserves id exactness instead of re-truncating to fp32
    compacted = dgl_graph_compact(g, nd.array([0.0, 1.0, 2.0, 3.0]),
                                  graph_sizes=[3])[0]
    assert compacted.data.asnumpy().dtype == onp.float64
    assert compacted.data.asnumpy()[0] == big


def test_kvstore_num_dead_node():
    """Reference kvstore.h:380 surface: local stores report 0; a live
    dist cluster reports 0 (jax.distributed has no partial-failure
    tracking — collectives fail outright instead)."""
    from mxnet_tpu import kvstore as kvs

    kv = kvs.create("local")
    assert kv.num_dead_node() == 0
    assert kv.num_dead_node(3) == 0


# ---- round-5 advice fixes -------------------------------------------------

def test_create_graph_replays_recorded_dropout_mask():
    """r5 advice (medium): eager stochastic ops must replay record-time
    PRNG keys under create_graph, not draw fresh ones."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd

    mx.random.seed(7)
    x = nd.array(onp.ones((4, 8), "f") * 3.0)
    x.attach_grad()
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5, mode="training")
        s = y.sum()
    mask = y.asnumpy() / 3.0
    g = autograd.grad(s, x, create_graph=True)
    onp.testing.assert_allclose(g.asnumpy(), mask, rtol=1e-6)

    # second order through the stochastic op
    mx.random.seed(11)
    x2 = nd.array(onp.full((4, 8), 2.0, "f"))
    x2.attach_grad()
    with autograd.record():
        d = nd.Dropout(x2, p=0.5, mode="training")
        z = (d * d).sum()
        gg = autograd.grad(z, x2, create_graph=True)
        s2 = (gg * gg).sum()
    m2 = d.asnumpy() / 2.0
    onp.testing.assert_allclose(gg.asnumpy(), 2 * 2.0 * m2 * m2, rtol=1e-5)
    s2.backward()
    onp.testing.assert_allclose(x2.grad.asnumpy(), 8 * 2.0 * m2 ** 4,
                                rtol=1e-4)


def test_ufunc_out_tuple_fills_caller_buffer():
    """r5 advice (low): numpy passes out= as a 1-tuple; the caller's
    buffer must be updated in place, not silently dropped."""
    import numpy as onp
    from mxnet_tpu import np as mnp

    a = mnp.array([1.0, 2.0])
    out = mnp.zeros((2,))
    r = onp.add(a, a, out=(out,))
    assert r is out
    assert out.asnumpy().tolist() == [2.0, 4.0]
    r2 = onp.sin(a, out=out)
    assert r2 is out


def test_child_scope_op_hook_labels():
    """r5 advice (low): a hook registered on a child while a parent-scope
    hook is active reports child-scoped labels, not the parent's."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    d1, d2 = nn.Dense(4), nn.Dense(2)
    net.add(d1, d2)
    net.initialize()
    parent, child = [], []
    h1 = net.register_op_hook(lambda name, arr: parent.append(name))
    h2 = d2.register_op_hook(lambda name, arr: child.append(name))
    net(nd.array(onp.ones((2, 3), "f")))
    assert child and all("." not in n for n in child), child
    h2.detach()
    child.clear()
    parent.clear()
    net(nd.array(onp.ones((2, 3), "f")))
    assert parent and not child
    h1.detach()
