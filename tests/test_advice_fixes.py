"""Regression tests for round-1 advisor findings (ADVICE.md r1).

Each test pins one of the five fixes: engine callback GC, flash-attention
causal shape guard, NaiveEngine version bump on error, persistent
calibration RNG, writable-recordio pickle guard.
"""
import pickle

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, engine, recordio
from mxnet_tpu.gluon import nn


def test_engine_callback_gc_after_wait_all():
    try:
        eng = engine.Engine(nthreads=2)
    except RuntimeError:
        pytest.skip("native engine unavailable")
    out = []
    for i in range(64):
        v = eng.new_variable()
        eng.push(lambda i=i: out.append(i), mutable_vars=(v,))
    eng.wait_all()
    assert len(out) == 64
    # full barrier -> every trampoline has returned; keepalives dropped
    assert eng.num_live_callbacks() == 0
    # poison survives GC: error pushed after the barrier still re-raises
    v = eng.new_variable()

    def boom():
        raise ValueError("poison")

    eng.push(boom, mutable_vars=(v,))
    with pytest.raises(ValueError, match="poison"):
        eng.wait_for_var(v)
    eng.wait_all()
    assert eng.num_live_callbacks() == 0


def test_flash_attention_causal_sq_gt_sk_raises():
    from mxnet_tpu.ops.flash_attention import flash_attention
    import jax.numpy as jnp

    q = jnp.zeros((1, 2, 8, 4))
    kv = jnp.zeros((1, 2, 4, 4))
    with pytest.raises(ValueError, match="S_q <= S_k"):
        flash_attention(q, kv, kv, causal=True)
    # non-causal cross-attention with S_q > S_k stays legal
    o = flash_attention(q, kv, kv, causal=False)
    assert o.shape == (1, 2, 8, 4)


def test_naive_engine_version_bump_on_error():
    eng = engine.NaiveEngine()
    v = eng.new_variable()
    eng.push(lambda: None, mutable_vars=(v,))
    assert eng.var_version(v) == 1

    def boom():
        raise RuntimeError("x")

    eng.push(boom, mutable_vars=(v,))
    # native Complete() bumps the version even on failure — match it
    assert eng.var_version(v) == 2
    with pytest.raises(RuntimeError):
        eng.wait_for_var(v)


def test_quant_entropy_reservoir_persistent_rng(monkeypatch):
    from mxnet_tpu.contrib import quantization as qz

    calls = []
    real = onp.random.RandomState

    class Recording(real):
        def __init__(self, *a, **kw):
            calls.append(a)
            super().__init__(*a, **kw)

    monkeypatch.setattr(onp.random, "RandomState", Recording)
    net = nn.Dense(4)
    net.initialize()
    rs = real(7)
    # 3 equal-size batches each larger than the 16384-sample reservoir cap
    batches = [nd.array(rs.randn(64, 600).astype("float32"))
               for _ in range(3)]
    qz.quantize_net(net, calib_data=batches, calib_mode="entropy")
    # one persistent RNG per quantize_net call, not one per batch
    assert len(calls) <= 1


def test_writable_recordio_pickle_raises(tmp_path):
    w = recordio.MXRecordIO(str(tmp_path / "a.rec"), "w")
    w.write(b"hello")
    with pytest.raises(RuntimeError, match="writable"):
        pickle.dumps(w)
    w.close()
    r = recordio.MXRecordIO(str(tmp_path / "a.rec"), "r")
    r2 = pickle.loads(pickle.dumps(r))  # readable pickling still works
    assert r2.read() == b"hello"
