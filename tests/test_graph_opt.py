"""Graph-optimization pass manager (analysis/graph_opt.py): golden
before/after snapshots per rewrite pass, idempotence, negative cases
(PRNG/effectful never merged, heads never eliminated), the shared
verify/optimize fact cache, and bitwise parity of optimized graphs
through all three lowering entry points (Executor bind, SymbolBlock
hybridize, serving InferenceSession)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.symbol as sym
from mxnet_tpu import autograd, nd
from mxnet_tpu.analysis import graph_opt
from mxnet_tpu.analysis.graph_opt import (RewritePass, _Graph,
                                          optimize_symbol)


def _ops(s):
    """Sorted op-name multiset of the graph's work list (vars excluded)
    — the golden-snapshot representation."""
    return sorted(n._op for n in _Graph(s).nodes if n._op is not None)


def _nodes(s):
    return len(_Graph(s).nodes)


@pytest.fixture(autouse=True)
def _fresh_counters():
    graph_opt.reset_counters()
    yield
    graph_opt.reset_counters()


# ---------------------------------------------------------------------------
# golden before/after snapshots, one per rewrite pass

def test_fold_golden():
    x = sym.var("x")
    c = sym.ones((2, 2)) + sym.zeros((2, 2))
    out = x + c
    assert _ops(out) == ["_sym_ones", "_sym_zeros", "broadcast_add",
                         "broadcast_add"]
    # fold alone replaces the const root in place; the orphaned
    # literals stay on the WORK LIST until dce drops them — the two
    # passes are separately observable in the per-pass node counts
    clean, st = optimize_symbol(out, level=1, passes=("fold", "dce"))
    fold_st, dce_st = st["passes"]
    assert (fold_st["rewrites"], dce_st["rewrites"]) == (1, 2)
    assert fold_st["nodes_before"] == fold_st["nodes_after"] == 5
    assert dce_st["nodes_after"] == 3
    assert not st["rejected"]
    assert _ops(clean) == ["_sym_constant", "broadcast_add"]
    feed = {"x": nd.array(onp.arange(4, dtype="f").reshape(2, 2))}
    assert onp.array_equal(out.eval_with(dict(feed)).asnumpy(),
                           clean.eval_with(dict(feed)).asnumpy())


def test_cse_golden():
    x, w = sym.var("x"), sym.var("w")
    out = (x * w) + (x * w)
    assert _ops(out) == ["broadcast_add", "broadcast_mul",
                         "broadcast_mul"]
    opt, st = optimize_symbol(out, level=1, passes=("cse",))
    assert st["rewrites"] == 1
    assert _ops(opt) == ["broadcast_add", "broadcast_mul"]
    feed = {"x": nd.array(onp.arange(4, dtype="f").reshape(2, 2)),
            "w": nd.array(onp.full((2, 2), 3.0, "f"))}
    assert onp.array_equal(out.eval_with(dict(feed)).asnumpy(),
                           opt.eval_with(dict(feed)).asnumpy())


def test_transpose_elision_golden():
    x, w = sym.var("x"), sym.var("w")
    out = x.transpose((1, 0)).transpose((1, 0)) + w
    assert _ops(out) == ["broadcast_add", "transpose", "transpose"]
    opt, st = optimize_symbol(out, level=1,
                              passes=("transpose_elision", "dce"))
    assert st["rewrites"] >= 1
    assert _ops(opt) == ["broadcast_add"]
    feed = {"x": nd.array(onp.arange(6, dtype="f").reshape(2, 3)),
            "w": nd.array(onp.ones((2, 3), "f"))}
    assert onp.array_equal(out.eval_with(dict(feed)).asnumpy(),
                           opt.eval_with(dict(feed)).asnumpy())


def test_transpose_pair_composes_to_net_permutation():
    x = sym.var("x")
    out = x.transpose((1, 2, 0)).transpose((1, 2, 0))
    opt, _ = optimize_symbol(out, level=1,
                             passes=("transpose_elision", "dce"))
    ts = [n for n in _Graph(opt).nodes if n._op == "transpose"]
    assert len(ts) == 1
    assert tuple(ts[0]._kwargs["axes"]) == (2, 0, 1)
    feed = {"x": nd.array(onp.arange(24, dtype="f").reshape(2, 3, 4))}
    assert onp.array_equal(out.eval_with(dict(feed)).asnumpy(),
                           opt.eval_with(dict(feed)).asnumpy())


def test_default_transpose_pair_is_identity():
    # axes=None is the full reversal; two of them cancel at any rank
    x = sym.var("x")
    out = x.transpose().transpose() + sym.var("w")
    opt, _ = optimize_symbol(out, level=1,
                             passes=("transpose_elision", "dce"))
    assert _ops(opt) == ["broadcast_add"]


def test_reshape_chain_collapses():
    x, w = sym.var("x"), sym.var("w")
    out = x.reshape((16,)).reshape((2, 8)) + w
    opt, _ = optimize_symbol(out, level=1,
                             passes=("transpose_elision", "dce"))
    rs = [n for n in _Graph(opt).nodes if n._op == "reshape"]
    assert len(rs) == 1
    assert tuple(rs[0]._kwargs["shape"]) == (2, 8)
    feed = {"x": nd.array(onp.arange(16, dtype="f").reshape(4, 4)),
            "w": nd.array(onp.ones((2, 8), "f"))}
    assert onp.array_equal(out.eval_with(dict(feed)).asnumpy(),
                           opt.eval_with(dict(feed)).asnumpy())


def test_identity_reshape_elided_under_known_shape():
    x, w = sym.var("x"), sym.var("w")
    out = x.reshape((4, 4)) + w
    opt, _ = optimize_symbol(out, shapes={"x": (4, 4)}, level=1,
                             passes=("transpose_elision", "dce"))
    assert _ops(opt) == ["broadcast_add"]
    # without the shape fact the reshape must stay (it may not be the
    # identity for some other binding)
    kept, st = optimize_symbol(out, level=1,
                               passes=("transpose_elision", "dce"))
    assert st["rewrites"] == 0 and kept is out


def test_dce_golden():
    x = sym.var("x")
    dead = x * sym.var("unused_w")
    out = sym.Group([x + x])
    # splice the dead producer into the walk via a group head, then
    # take only the live head: build a graph where the work list holds
    # an orphan by construction — fold's replacement does this in real
    # pipelines; here the simplest observable case is post-CSE orphans
    a, b = x * x, x * x
    g = a + b
    opt, st = optimize_symbol(g, level=1, passes=("cse", "dce"))
    assert _ops(opt) == ["broadcast_add", "broadcast_mul"]
    assert st["rewrites"] >= 1
    del dead, out


# ---------------------------------------------------------------------------
# pipeline behavior

def test_level2_fixpoint_and_idempotence(monkeypatch):
    # fusion off: this golden pins the round-14 fold/cse/elision/dce
    # behavior (with fusion on, the surviving mul+add cluster becomes
    # one _fused_elementwise — covered by tests/test_fusion.py)
    monkeypatch.setenv("MXNET_FUSION", "0")
    x, w = sym.var("x"), sym.var("w")
    t = x.transpose((1, 0)).transpose((1, 0))
    out = (t * w) + (x * w) + (sym.ones((4, 4)) + sym.ones((4, 4)))
    opt, st = optimize_symbol(out, shapes={"x": (4, 4), "w": (4, 4)},
                              level=2)
    assert st["nodes_after"] < st["nodes_before"]
    # elision exposes t*w == x*w only on the second iteration; the
    # fixpoint (level 2) must reach it
    muls = [n for n in _Graph(opt).nodes if n._op == "broadcast_mul"]
    assert len(muls) == 1
    # idempotence: a second run over the optimized graph is a no-op
    again, st2 = optimize_symbol(opt, level=2)
    assert st2["rewrites"] == 0
    assert again is opt
    feed = {"x": nd.array(onp.arange(16, dtype="f").reshape(4, 4)),
            "w": nd.array(onp.full((4, 4), 2.0, "f"))}
    assert onp.array_equal(out.eval_with(dict(feed)).asnumpy(),
                           opt.eval_with(dict(feed)).asnumpy())


def test_per_pass_stats_and_counters():
    x = sym.var("x")
    out = (x * x) + (x * x)
    _, st = optimize_symbol(out, level=1)
    names = [p["pass"] for p in st["passes"]]
    assert names == ["fold", "cse", "transpose_elision", "fusion",
                     "dce"]
    for p in st["passes"]:
        assert p["nodes_before"] >= p["nodes_after"]
        assert p["time_ms"] >= 0
    c = graph_opt.counters()
    assert c["graphs_optimized"] == 1
    assert c["cse_rewrites"] == 1
    assert c["nodes_before_total"] > c["nodes_after_total"]
    from mxnet_tpu import profiler
    assert profiler.graph_opt_counters()["graphs_optimized"] == 1


def test_level0_is_passthrough():
    x = sym.var("x")
    out = (x * x) + (x * x)
    opt, st = optimize_symbol(out, level=0)
    assert opt is out and st["rewrites"] == 0
    assert graph_opt.counters()["graphs_seen"] == 0


def test_opt_level_reads_env(monkeypatch):
    monkeypatch.delenv("MXNET_GRAPH_OPT", raising=False)
    assert graph_opt.opt_level() == 0
    monkeypatch.setenv("MXNET_GRAPH_OPT", "2")
    assert graph_opt.opt_level() == 2
    assert graph_opt.graph_opt_enabled()
    monkeypatch.setenv("MXNET_GRAPH_OPT", "7")
    assert graph_opt.opt_level() == 2  # clamped
    from mxnet_tpu import runtime
    assert runtime._detect()["GRAPH_OPT"] is True


def test_fingerprint_salt_versions_artifacts(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_OPT", "0")
    s0 = graph_opt.fingerprint_salt()
    monkeypatch.setenv("MXNET_GRAPH_OPT", "2")
    s2 = graph_opt.fingerprint_salt()
    assert s0 != s2
    assert graph_opt.PIPELINE_VERSION in s2
    assert graph_opt.PIPELINE_VERSION not in s0


# ---------------------------------------------------------------------------
# negative cases: what must NOT be rewritten

def test_prng_ops_never_cse():
    x = sym.var("x")
    d1 = sym.dropout(x, p=0.5)
    d2 = sym.dropout(x, p=0.5)
    out = sym.Group([d1, d2])
    opt, st = optimize_symbol(out, level=2)
    assert st["rewrites"] == 0 and opt is out
    assert not graph_opt.op_is_pure("dropout")


def test_effectful_ops_never_merged():
    x = sym.var("x")
    args = [sym.var(n) for n in ("g", "b", "mm", "mv")]
    b1 = sym.batch_norm(x, *args)
    b2 = sym.batch_norm(x, *args)
    out = sym.Group([b1, b2])
    opt, st = optimize_symbol(out, level=2)
    assert st["rewrites"] == 0 and opt is out
    assert not graph_opt.op_is_pure("batch_norm")


def test_prng_ops_never_folded():
    # a PRNG op over constant inputs must NOT be frozen to one draw
    c = sym.ones((2, 2))
    d = sym.dropout(c, p=0.5)
    opt, st = optimize_symbol(d, level=2)
    assert "dropout" in _ops(opt)


def test_group_heads_survive_dce():
    # every head is a DCE root: a Group output consumed by nothing
    # else (a grad_req output, an aux head) must never be eliminated
    x = sym.var("x")
    side = x * sym.var("w_side")
    main = x + x
    out = sym.Group([main, side])
    opt, _ = optimize_symbol(out, level=2)
    assert len(_Graph(opt).heads) == 2
    assert "broadcast_mul" in _ops(opt)


def test_positional_reshape_codes_not_collapsed():
    # 0 / -2 / -3 / -4 reshape codes depend on the INPUT shape; the
    # outer spec here is position-dependent, so the chain must stay
    x = sym.var("x")
    out = x.reshape((2, 8)).reshape((0, -1))
    opt, st = optimize_symbol(out, level=2)
    assert st["rewrites"] == 0 and opt is out


def test_bad_rewrite_is_rejected_by_post_verify():
    from mxnet_tpu.symbol import Symbol

    def breaker(graph, ctx):
        head = graph.heads[0]
        bad = Symbol(op="zz_unregistered_op", name=head._name,
                     inputs=list(head._inputs), kwargs={})
        graph.apply({graph_opt._key(head): bad})
        return 1

    x = sym.var("x")
    out = x + x
    opt, st = optimize_symbol(
        out, level=1, passes=[RewritePass("breaker", breaker)])
    assert opt is out
    assert st["rejected"] is True
    assert graph_opt.counters()["graphs_rejected"] == 1


def test_oversized_fold_is_skipped(monkeypatch):
    monkeypatch.setattr(graph_opt, "_FOLD_MAX_ELEMENTS", 8)
    c = sym.ones((4, 4)) + sym.ones((4, 4))  # 16 elements > cap
    opt, st = optimize_symbol(c + sym.var("x"), level=1,
                              passes=("fold", "dce"))
    assert "_sym_constant" not in _ops(opt)


# ---------------------------------------------------------------------------
# satellite: one fact cache across verify-then-optimize

def test_verify_then_optimize_infers_shapes_once(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_VERIFY", "error")
    monkeypatch.setenv("MXNET_GRAPH_OPT", "1")
    graph_opt.reset_counters()
    x, w = sym.var("x"), sym.var("w")
    s = (x * w) + (x * w)
    ex = s.simple_bind(x=(4, 4), w=(4, 4))
    c = graph_opt.counters()
    # exactly two inference runs: ONE shared by the verifier pipeline
    # and the rewrite passes (the bind-time PassContext fact cache),
    # plus ONE for the post-pass re-verification of the optimized graph
    assert c["shape_analysis_runs"] == 2, c
    assert c["dtype_analysis_runs"] == 2, c
    assert c["fact_cache_hits"] >= 1, c
    assert c["graphs_optimized"] == 1
    assert _ops(ex._symbol) == ["broadcast_add", "broadcast_mul"]


def test_fact_cache_memoizes_within_context():
    from mxnet_tpu.analysis import PassContext

    x = sym.var("x")
    ctx = PassContext(x + x, shapes={"x": (2, 2)})
    graph_opt.reset_counters()
    first = ctx.fact("shapes")
    again = ctx.fact("shapes")
    assert first is again
    c = graph_opt.counters()
    assert c["shape_analysis_runs"] == 1
    assert c["fact_cache_hits"] == 1
    # analysis passes are typed objects over the same cache
    assert graph_opt.purity_analysis.run(ctx) == {"broadcast_add": True}
    assert ("var", "x") in graph_opt.reachability_analysis.run(ctx)


# ---------------------------------------------------------------------------
# entry point 1: Executor bind

def _dup_graph():
    data, w = sym.var("data"), sym.var("w")
    t = data.transpose((1, 0)).transpose((1, 0))
    c = sym.ones((4, 4)) + sym.ones((4, 4))
    return (t * w) + (data * w) + c


def _bind_forward_backward(monkeypatch, level):
    monkeypatch.setenv("MXNET_GRAPH_OPT", str(level))
    ex = _dup_graph().simple_bind(data=(4, 4), w=(4, 4))
    ex.arg_dict["data"]._data = nd.array(
        onp.arange(16, dtype="f").reshape(4, 4)).data
    ex.arg_dict["w"]._data = nd.array(
        onp.full((4, 4), 2.0, "f")).data
    outs = ex.forward(is_train=True)
    ex.backward()
    return (ex, outs[0].asnumpy(),
            {k: v.asnumpy() for k, v in ex.grad_dict.items()})


def test_bind_parity_and_node_reduction(monkeypatch):
    ex0, y0, g0 = _bind_forward_backward(monkeypatch, 0)
    ex2, y2, g2 = _bind_forward_backward(monkeypatch, 2)
    assert onp.array_equal(y0, y2)  # bitwise, integer-exact values
    assert set(g0) == set(g2)
    for k in g0:
        assert onp.array_equal(g0[k], g2[k]), k
    assert _nodes(ex2._symbol) < _nodes(ex0._symbol)
    assert _nodes(ex0._symbol) == _nodes(_dup_graph())


# ---------------------------------------------------------------------------
# entry point 2: SymbolBlock forward / hybridize (CachedOp)

def _paramless_block():
    x = sym.var("x")
    g = (x * x) + (x * x) + (sym.ones((1, 8)) + sym.ones((1, 8)))
    return mx.gluon.SymbolBlock(g, [sym.var("x")])


def test_symbolblock_hybridize_parity(monkeypatch):
    xval = nd.array(onp.arange(16, dtype="f").reshape(2, 8))
    monkeypatch.setenv("MXNET_GRAPH_OPT", "0")
    net0 = _paramless_block()
    with autograd.pause(train_mode=False):
        y_eager0 = net0(xval).asnumpy()
    monkeypatch.setenv("MXNET_GRAPH_OPT", "2")
    net2 = _paramless_block()
    net2.hybridize()
    with autograd.pause(train_mode=False):
        y_opt = net2(xval).asnumpy()
    assert onp.array_equal(y_eager0, y_opt)
    # the rewrite actually reached the evaluated graph
    assert _nodes(net2._optimized_outputs()) < _nodes(net2._outputs)
    assert graph_opt.counters()["graphs_optimized"] >= 1


def test_symbolblock_opt_cache_tracks_level(monkeypatch):
    net = _paramless_block()
    monkeypatch.setenv("MXNET_GRAPH_OPT", "0")
    assert net._optimized_outputs() is net._outputs
    monkeypatch.setenv("MXNET_GRAPH_OPT", "2")
    opt_a = net._optimized_outputs()
    opt_b = net._optimized_outputs()
    assert opt_a is opt_b  # cached per (level, pipeline version)
    assert opt_a is not net._outputs
    c = graph_opt.counters()["graphs_seen"]
    net._optimized_outputs()
    assert graph_opt.counters()["graphs_seen"] == c  # no re-run


# ---------------------------------------------------------------------------
# entry point 3: serving InferenceSession

def test_serving_session_parity_and_fingerprint(monkeypatch):
    from mxnet_tpu import serving

    x = onp.arange(16, dtype="f").reshape(2, 8)

    monkeypatch.setenv("MXNET_GRAPH_OPT", "0")
    sess0 = serving.InferenceSession(
        _paramless_block(), input_shapes=[(1, 8)], buckets=[1, 2],
        warm=False)
    y0 = sess0.predict(nd.array(x)).asnumpy()

    monkeypatch.setenv("MXNET_GRAPH_OPT", "2")
    sess2 = serving.InferenceSession(
        _paramless_block(), input_shapes=[(1, 8)], buckets=[1, 2],
        warm=False)
    y2 = sess2.predict(nd.array(x)).asnumpy()

    assert onp.array_equal(y0, y2)
    assert graph_opt.counters()["graphs_optimized"] >= 1

    # the compile-cache fingerprint must key on the pass-pipeline
    # version so optimized and unoptimized AOT artifacts never collide
    fp2 = sess2._fingerprint(2, 0)
    monkeypatch.setenv("MXNET_GRAPH_OPT", "0")
    fp0 = sess2._fingerprint(2, 0)
    assert fp0 is not None and fp2 is not None
    assert fp0 != fp2
    assert sess2._fingerprint(2, 0) == fp0  # deterministic
