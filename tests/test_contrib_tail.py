"""Contrib op tail: fft/ifft, count_sketch, deformable conv, proposal,
psroi pooling, mrcnn mask targets.

Reference coverage model: tests/python/unittest/test_operator.py
test_laop-style value checks + tests/python/gpu/test_operator_gpu.py
test_deformable_convolution/test_psroipooling (numeric checks vs naive
implementations).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal

rs = onp.random.RandomState(9)


def test_fft_ifft_roundtrip():
    x = rs.randn(4, 16).astype("f")
    out = nd.contrib.fft(nd.array(x))
    assert out.shape == (4, 32)
    ref = onp.fft.fft(x, axis=-1)
    inter = onp.stack([ref.real, ref.imag], -1).reshape(4, 32)
    assert_almost_equal(out.asnumpy(), inter.astype("f"), rtol=1e-3,
                        atol=1e-3)
    # cuFFT-style unnormalized inverse: ifft(fft(x)) == d * x
    back = nd.contrib.ifft(out)
    assert_almost_equal(back.asnumpy(), 16 * x, rtol=1e-3, atol=1e-3)


def test_fft_gradient():
    x = rs.randn(2, 8).astype("f")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.sum(nd.contrib.fft(a))
    y.backward()
    assert a.grad.shape == (2, 8)
    assert onp.isfinite(a.grad.asnumpy()).all()


def test_count_sketch():
    n, d, od = 3, 10, 6
    x = rs.randn(n, d).astype("f")
    h = rs.randint(0, od, (1, d))
    s = rs.choice([-1, 1], (1, d)).astype("f")
    out = nd.contrib.count_sketch(nd.array(x), nd.array(h.astype("f")),
                                  nd.array(s), out_dim=od)
    expect = onp.zeros((n, od), "f")
    for i in range(d):
        expect[:, h[0, i]] += s[0, i] * x[:, i]
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-5)


def _naive_deform_conv(x, off, w, stride, pad, dilate):
    """Scalar-loop oracle for deformable convolution (no groups)."""
    B, C, H, W = x.shape
    F, _, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    def bil(img, y, x_):
        if y <= -1 or y >= img.shape[0] or x_ <= -1 or x_ >= img.shape[1]:
            return 0.0
        y0, x0 = int(onp.floor(y)), int(onp.floor(x_))
        vy, vx = y - y0, x_ - x0
        tot = 0.0
        for (yy, xx, wgt) in [(y0, x0, (1 - vy) * (1 - vx)),
                              (y0, x0 + 1, (1 - vy) * vx),
                              (y0 + 1, x0, vy * (1 - vx)),
                              (y0 + 1, x0 + 1, vy * vx)]:
            if 0 <= yy < img.shape[0] and 0 <= xx < img.shape[1]:
                tot += wgt * img[yy, xx]
        return tot

    out = onp.zeros((B, F, Ho, Wo), "f")
    for b in range(B):
        for f in range(F):
            for oy in range(Ho):
                for ox in range(Wo):
                    acc = 0.0
                    for c in range(C):
                        for i in range(kh):
                            for j in range(kw):
                                k = i * kw + j
                                y = oy * sh - ph + i * dh + \
                                    off[b, 2 * k, oy, ox]
                                x_ = ox * sw - pw + j * dw + \
                                    off[b, 2 * k + 1, oy, ox]
                                acc += w[f, c, i, j] * bil(x[b, c], y, x_)
                    out[b, f, oy, ox] = acc
    return out


def test_deformable_convolution_matches_naive():
    x = rs.randn(1, 2, 6, 6).astype("f")
    w = rs.randn(3, 2, 3, 3).astype("f")
    off = (rs.rand(1, 18, 6, 6).astype("f") - 0.5)
    out = nd.contrib.deformable_convolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        stride=(1, 1), pad=(1, 1), dilate=(1, 1), num_filter=3,
        no_bias=True)
    ref = _naive_deform_conv(x, off, w, (1, 1), (1, 1), (1, 1))
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)


def test_deformable_conv_zero_offset_equals_conv():
    x = rs.randn(2, 3, 8, 8).astype("f")
    w = rs.randn(4, 3, 3, 3).astype("f")
    off = onp.zeros((2, 18, 8, 8), "f")
    out = nd.contrib.deformable_convolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        pad=(1, 1), num_filter=4, no_bias=True)
    ref = nd.convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         pad=(1, 1), num_filter=4, no_bias=True)
    assert_almost_equal(out.asnumpy(), ref.asnumpy(), rtol=1e-3,
                        atol=1e-4)


def test_deformable_conv_gradient():
    x = rs.randn(1, 2, 5, 5).astype("f")
    w = rs.randn(2, 2, 3, 3).astype("f")
    off = (rs.rand(1, 18, 5, 5).astype("f") - 0.5) * 0.1
    xs, offs, ws = nd.array(x), nd.array(off), nd.array(w)
    for a in (xs, offs, ws):
        a.attach_grad()
    with autograd.record():
        out = nd.contrib.deformable_convolution(
            xs, offs, ws, kernel=(3, 3), pad=(1, 1), num_filter=2,
            no_bias=True)
        loss = nd.sum(out)
    loss.backward()
    for a in (xs, offs, ws):
        assert onp.isfinite(a.grad.asnumpy()).all()
        assert onp.abs(a.grad.asnumpy()).sum() > 0


def test_proposal_shapes_and_validity():
    K = 3 * 4  # ratios x scales (defaults: 3 ratios, 4 scales)
    h = w = 4
    cls = rs.rand(2, 2 * K, h, w).astype("f")
    bbox = (rs.rand(2, 4 * K, h, w).astype("f") - 0.5) * 0.1
    im_info = onp.array([[64, 64, 1.0], [64, 64, 1.0]], "f")
    rois = nd.contrib.proposal(
        nd.array(cls), nd.array(bbox), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10, threshold=0.7,
        rpn_min_size=4)
    assert rois.shape == (20, 5)
    r = rois.asnumpy()
    assert set(onp.unique(r[:, 0])) <= {0.0, 1.0}
    assert (r[:10, 0] == 0).all() and (r[10:, 0] == 1).all()
    # boxes inside the image
    assert (r[:, 1] >= 0).all() and (r[:, 3] <= 63).all()
    assert (r[:, 2] >= 0).all() and (r[:, 4] <= 63).all()
    # with scores
    rois2, sc = nd.contrib.proposal(
        nd.array(cls), nd.array(bbox), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10, output_score=True)
    assert sc.shape == (20, 1)


def test_psroi_pooling_constant_plane():
    """On a channel-constant input each output cell equals its source
    channel's constant (position-sensitive channel mapping check)."""
    P, D = 2, 3
    C = D * P * P
    x = onp.zeros((1, C, 8, 8), "f")
    for c in range(C):
        x[0, c] = c
    rois = onp.array([[0, 0, 0, 7, 7]], "f")
    out = nd.contrib.psroi_pooling(nd.array(x), nd.array(rois),
                                   spatial_scale=1.0, output_dim=D,
                                   pooled_size=P)
    assert out.shape == (1, D, P, P)
    o = out.asnumpy()[0]
    for d in range(D):
        for i in range(P):
            for j in range(P):
                expect = (d * P + i) * P + j
                assert abs(o[d, i, j] - expect) < 1e-4, (d, i, j, o[d])


def test_psroi_pooling_gradient():
    P, D = 2, 2
    C = D * P * P
    x = nd.array(rs.randn(1, C, 6, 6).astype("f"))
    rois = nd.array(onp.array([[0, 1, 1, 4, 4]], "f"))
    x.attach_grad()
    with autograd.record():
        out = nd.contrib.psroi_pooling(x, rois, spatial_scale=1.0,
                                       output_dim=D, pooled_size=P)
        loss = nd.sum(out)
    loss.backward()
    assert onp.abs(x.grad.asnumpy()).sum() > 0


def test_deformable_psroi_pooling_no_trans_matches_psroi_roughly():
    P, D = 2, 2
    C = D * P * P
    x = rs.randn(1, C, 8, 8).astype("f")
    rois = onp.array([[0, 0, 0, 7, 7]], "f")
    out = nd.contrib.deformable_psroi_pooling(
        nd.array(x), nd.array(rois), spatial_scale=1.0, output_dim=D,
        group_size=P, pooled_size=P, sample_per_part=4, no_trans=True)
    assert out.shape == (1, D, P, P)
    assert onp.isfinite(out.asnumpy()).all()


def test_mrcnn_mask_target():
    B, N, M = 1, 2, 3
    rois = onp.array([[[2, 2, 10, 10], [0, 0, 6, 6]]], "f")
    masks = onp.zeros((B, M, 16, 16), "f")
    masks[0, 1, :, :8] = 1.0  # left half on
    matches = onp.array([[1, 0]], "f")
    cls_t = onp.array([[2, 1]], "f")
    targets, weights = nd.contrib.mrcnn_mask_target(
        nd.array(rois), nd.array(masks), nd.array(matches),
        nd.array(cls_t), num_rois=N, num_classes=4, mask_size=(4, 4))
    assert targets.shape == (1, 2, 4, 4, 4)
    assert weights.shape == (1, 2, 4, 4, 4)
    wn = weights.asnumpy()
    assert wn[0, 0, 2].sum() == 16 and wn[0, 0, 1].sum() == 0
    assert wn[0, 1, 1].sum() == 16
    # roi 0 covers x 2..10 of a mask whose left half (x<8) is 1
    t = targets.asnumpy()[0, 0, 2]
    assert t[:, 0].mean() > 0.9 and t[:, 3].mean() < 0.1


def test_contrib_tail_camelcase_aliases():
    for name in ("Proposal", "MultiProposal", "PSROIPooling",
                 "DeformableConvolution", "DeformablePSROIPooling"):
        assert hasattr(nd.contrib, name)


# ---- contrib rnn cells (conv + variational dropout + LSTMP) --------------

def test_conv2d_lstm_cell_unroll():
    from mxnet_tpu.gluon.contrib.rnn import Conv2DLSTMCell

    mx.random.seed(0)
    cell = Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=5,
                          i2h_kernel=3, h2h_kernel=3)
    cell.initialize(mx.init.Xavier())
    x = nd.array(onp.random.RandomState(0).rand(4, 2, 3, 8, 8)
                 .astype("f"))  # (T, N, C, H, W) under TNC layout
    outputs, states = cell.unroll(4, x, layout="TNC",
                                  merge_outputs=False)
    assert len(outputs) == 4
    assert outputs[0].shape == (2, 5, 8, 8)
    assert states[0].shape == (2, 5, 8, 8)  # h
    assert states[1].shape == (2, 5, 8, 8)  # c


def test_conv1d_gru_and_rnn_cells():
    from mxnet_tpu.gluon.contrib.rnn import Conv1DGRUCell, Conv1DRNNCell

    for cls, nstates in ((Conv1DGRUCell, 1), (Conv1DRNNCell, 1)):
        cell = cls(input_shape=(2, 10), hidden_channels=4)
        cell.initialize()
        x = nd.ones((3, 2, 10))
        states = cell.begin_state(batch_size=3)
        assert len(states) == nstates
        out, new_states = cell(x, states)
        assert out.shape == (3, 4, 10)


def test_variational_dropout_mask_constant_across_steps():
    from mxnet_tpu.gluon.contrib.rnn import VariationalDropoutCell
    from mxnet_tpu.gluon.rnn import RNNCell
    from mxnet_tpu import autograd

    mx.random.seed(3)
    base = RNNCell(6)
    cell = VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize()
    with autograd.record():  # dropout actually samples in train mode
        cell.reset()
        x = nd.ones((2, 4))
        s = cell.begin_state(batch_size=2)
        cell(x, s)
        m1 = cell._input_mask.asnumpy()
        cell(x, s)
        m2 = cell._input_mask.asnumpy()
    onp.testing.assert_array_equal(m1, m2)  # SAME mask both steps
    with autograd.record():
        cell.reset()  # new unroll -> new mask (overwhelmingly likely)
        cell(x, s)
        m3 = cell._input_mask.asnumpy()
    assert m1.shape == m3.shape
    assert (m1 != m3).any()


def test_lstmp_cell_projection_shapes():
    from mxnet_tpu.gluon.contrib.rnn import LSTMPCell

    cell = LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize()
    x = nd.ones((5, 7))
    states = cell.begin_state(batch_size=5)
    assert states[0].shape == (5, 3)   # projected recurrent state
    assert states[1].shape == (5, 8)   # cell state
    out, (r, c) = cell(x, states)
    assert out.shape == (5, 3)
    assert r.shape == (5, 3) and c.shape == (5, 8)


def test_legacy_contrib_autograd_api():
    """reference contrib/autograd.py: the pre-mx.autograd experimental
    surface (train_section, grad_and_loss, compute_gradient...)."""
    import numpy as onp

    from mxnet_tpu import nd
    from mxnet_tpu.contrib import autograd as cag

    @cag.grad_and_loss
    def f(a, b):
        return a * b

    a = nd.array(onp.array([2.0], "f"))
    b = nd.array(onp.array([3.0], "f"))
    grads, out = f(a, b)
    assert float(out.asnumpy()[0]) == 6.0
    assert [float(g.asnumpy()[0]) for g in grads] == [3.0, 2.0]

    @cag.grad
    def g(a):
        return a * a

    (ga,) = g(nd.array(onp.array([4.0], "f")))
    assert float(ga.asnumpy()[0]) == 8.0

    x = nd.array(onp.ones(3, "f"))
    x.attach_grad()
    with cag.train_section():
        y = (x * x).sum()
    cag.compute_gradient([y])
    assert x.grad.asnumpy().tolist() == [2.0] * 3
    # test_section suspends recording
    with cag.train_section():
        with cag.test_section():
            from mxnet_tpu import autograd as ag

            assert not ag.is_recording()
        assert True


def test_legacy_contrib_dataloader_iter():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ds = ArrayDataset(nd.array(onp.arange(8, dtype="f").reshape(4, 2)),
                      nd.array(onp.arange(4, dtype="f")))
    it = mx.contrib.io.DataLoaderIter(DataLoader(ds, batch_size=2))
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (2, 2)
    it.reset()
    assert len(list(it)) == 2
