"""Optimizer update rules against closed-form numpy math (reference:
tests/python/unittest/test_optimizer.py — each rule's single-step
update compared exactly, plus wd/rescale/clip plumbing and schedules)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, optimizer as opt
from mxnet_tpu.test_utils import assert_almost_equal

W0 = onp.array([0.5, -1.0, 2.0, 0.1], "f")
G0 = onp.array([0.2, -0.4, 0.6, -0.8], "f")


def _step(optimizer, w=W0, g=G0, steps=1):
    """Run `steps` updates through the Updater machinery (the kvstore
    server path) and return the resulting weight."""
    upd = opt.get_updater(optimizer)
    wn = nd.array(w.copy())
    for _ in range(steps):
        upd(0, nd.array(g.copy()), wn)
    return wn.asnumpy()


def test_sgd_plain():
    got = _step(opt.SGD(learning_rate=0.1, wd=0.0))
    assert_almost_equal(got, W0 - 0.1 * G0, rtol=1e-6)


def test_sgd_weight_decay():
    wd = 0.01
    got = _step(opt.SGD(learning_rate=0.1, wd=wd))
    assert_almost_equal(got, W0 - 0.1 * (G0 + wd * W0), rtol=1e-6)


def test_sgd_momentum_two_steps():
    lr, mom = 0.1, 0.9
    got = _step(opt.SGD(learning_rate=lr, momentum=mom, wd=0.0), steps=2)
    m = -lr * G0
    w = W0 + m
    m = mom * m - lr * G0
    w = w + m
    assert_almost_equal(got, w, rtol=1e-6)


def test_sgd_rescale_and_clip():
    o = opt.SGD(learning_rate=1.0, wd=0.0, rescale_grad=0.5,
                clip_gradient=0.2)
    got = _step(o)
    g = onp.clip(G0 * 0.5, -0.2, 0.2)
    assert_almost_equal(got, W0 - g, rtol=1e-6)


def test_adam_first_step_formula():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    got = _step(opt.Adam(learning_rate=lr, beta1=b1, beta2=b2,
                         epsilon=eps, wd=0.0))
    m = (1 - b1) * G0
    v = (1 - b2) * G0 * G0
    mhat = m / (1 - b1)
    vhat = v / (1 - b2)
    want = W0 - lr * mhat / (onp.sqrt(vhat) + eps)
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-7)


def test_adagrad_accumulates():
    lr, eps = 0.5, 1e-7
    got = _step(opt.AdaGrad(learning_rate=lr, eps=eps, wd=0.0), steps=2)
    h = G0 * G0
    w = W0 - lr * G0 / onp.sqrt(h + eps)
    h = h + G0 * G0
    w = w - lr * G0 / onp.sqrt(h + eps)
    assert_almost_equal(got, w, rtol=1e-5)


def test_rmsprop_formula():
    lr, rho, eps = 0.01, 0.9, 1e-8
    got = _step(opt.RMSProp(learning_rate=lr, gamma1=rho, epsilon=eps,
                            wd=0.0, centered=False))
    e = (1 - rho) * G0 * G0
    want = W0 - lr * G0 / onp.sqrt(e + eps)
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-6)


def test_signum_sign_update():
    lr = 0.1
    got = _step(opt.Signum(learning_rate=lr, momentum=0.0, wd=0.0))
    assert_almost_equal(got, W0 - lr * onp.sign(G0), rtol=1e-6)


def test_lr_scheduler_factor():
    sched = opt.lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                             base_lr=1.0)
    # drops AFTER each `step` updates (reference: count+step threshold)
    assert sched(1) == 1.0
    assert sched(3) == 0.5
    assert sched(5) == 0.25


def test_lr_scheduler_warmup_cosine():
    sched = opt.lr_scheduler.CosineScheduler(
        max_update=10, base_lr=1.0, final_lr=0.0, warmup_steps=2)
    assert sched(0) < sched(1) <= 1.0  # warmup climbs
    assert sched(10) <= sched(5) <= 1.0  # cosine decays


def test_optimizer_registry_create():
    for name in ("sgd", "adam", "adagrad", "rmsprop", "adadelta",
                 "adamax", "nadam", "ftrl", "nag", "signum", "lamb"):
        o = opt.create(name, learning_rate=0.1)
        assert isinstance(o, opt.Optimizer), name


def test_lr_wd_mult_apply():
    o = opt.SGD(learning_rate=1.0, wd=0.1)
    o.set_lr_mult({"w": 0.5})
    o.set_wd_mult({"w": 0.0})
    # through the updater with named index mapping
    upd = opt.get_updater(o)
    wn = nd.array(W0.copy())
    # map integer index to the named mult via idx2name
    o.idx2name = {0: "w"}
    upd(0, nd.array(G0.copy()), wn)
    assert_almost_equal(wn.asnumpy(), W0 - 0.5 * G0, rtol=1e-6)


def test_updater_states_roundtrip():
    import pickle

    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    w = nd.array(W0.copy())
    upd(0, nd.array(G0.copy()), w)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    # continuing from restored momentum must equal continuing original
    w1 = nd.array(w.asnumpy().copy())
    w2 = nd.array(w.asnumpy().copy())
    upd(0, nd.array(G0.copy()), w1)
    upd2(0, nd.array(G0.copy()), w2)
    assert_almost_equal(w1.asnumpy(), w2.asnumpy(), rtol=1e-6)


def test_multi_precision_fp16_masters():
    o = opt.SGD(learning_rate=0.1, momentum=0.9,
                multi_precision=True)
    upd = opt.get_updater(o)
    w16 = nd.array(W0.copy()).astype("float16")
    for _ in range(3):
        upd(0, nd.array(G0.copy()).astype("float16"), w16)
    # fp32 reference trajectory
    m = onp.zeros_like(W0)
    w = W0.copy()
    for _ in range(3):
        m = 0.9 * m - 0.1 * G0
        w = w + m
    assert_almost_equal(w16.asnumpy().astype("f"), w, rtol=2e-2,
                        atol=2e-3)
