// Native dependency engine + pooled storage for mxnet_tpu.
//
// TPU-native equivalent of the reference's core runtime C++ (SURVEY §2.1):
//  - ThreadedEngine dependency scheduling: versioned vars, ops with
//    const/mutable var sets, per-var waiter FIFOs (the reference's
//    VersionedVarBlock lists, src/engine/threaded_engine.h:120-229),
//    worker thread pool with priorities, async exception capture and
//    propagation to dependent ops' vars (threaded_engine.h:310,466-498)
//  - pooled storage manager: exact-size bucket recycling with stats
//    (reference: src/storage/pooled_storage_manager.h:52-94)
//
// On TPU the XLA runtime already sequences device computations, so this
// engine schedules the HOST side: IO pipelines, checkpoint writes, custom
// op bodies — anything the reference pushed to its CPU workers. Exposed
// as a flat C ABI consumed via ctypes (mxnet_tpu/engine.py).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Callback = int (*)(void*);  // user fn: 0 ok, nonzero = failed

struct Op;

struct Var {
  std::deque<std::pair<Op*, bool>> waiters;  // (op, is_write)
  int active_readers = 0;
  bool active_writer = false;
  uint64_t version = 0;
  bool has_error = false;
  int64_t error_op = -1;  // op id that poisoned this var
};

struct Op {
  int64_t id;
  Callback fn;
  void* ctx;
  std::vector<int64_t> const_vars;
  std::vector<int64_t> mutable_vars;
  std::atomic<int> missing{0};  // ungranted deps
  int priority = 0;
  int lane = 0;  // worker-pool lane (ThreadedEnginePerDevice analog:
                 // lane 0 = compute, lane 1 = copy/IO, ...)
  bool always_run = false;  // run even when inputs are poisoned (internal
                            // WaitForVar sync ops must fire their cv)
};

struct OpCmp {
  bool operator()(Op* a, Op* b) const {
    if (a->priority != b->priority) return a->priority < b->priority;
    return a->id > b->id;  // FIFO within priority
  }
};

class Engine {
 public:
  // nlanes worker pools share ONE dependency/var state: the reference's
  // ThreadedEnginePerDevice runs a pool per device plus dedicated copy
  // workers (threaded_engine_perdevice.cc) so slow IO ops can't starve
  // compute ops; on TPU device compute is XLA-async so the lanes that
  // matter are compute vs host-side copy/IO.
  explicit Engine(int nthreads, int nlanes = 1)
      : shutdown_(false), inflight_(0) {
    if (nthreads < 1) nthreads = 1;
    if (nlanes < 1) nlanes = 1;
    ready_.resize(nlanes);
    lane_cv_ = std::vector<std::condition_variable>(nlanes);
    // total thread count honors nthreads (MXNET_CPU_WORKER_NTHREADS):
    // auxiliary lanes (copy/IO) get 1 worker each like the reference's
    // small copy pools, the compute lane keeps the rest. Floor: every
    // lane needs >=1 worker (a zero-worker lane would deadlock its
    // queue), so with nthreads <= nlanes-1 the total is nlanes.
    int aux = nlanes - 1;
    int lane0 = nthreads > aux ? nthreads - aux : 1;
    for (int l = 0; l < nlanes; ++l) {
      int n = (l == 0) ? lane0 : 1;
      for (int i = 0; i < n; ++i)
        workers_.emplace_back([this, l] { WorkerLoop(l); });
    }
  }

  ~Engine() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      shutdown_ = true;
      for (auto& c : lane_cv_) c.notify_all();
    }
    for (auto& t : workers_) t.join();
    for (auto& kv : vars_) delete kv.second;
  }

  int64_t NewVar() {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_[id] = new Var();
    return id;
  }

  int64_t Push(Callback fn, void* ctx, const int64_t* cvars, int ncon,
               const int64_t* mvars, int nmut, int priority,
               bool always_run = false, int lane = 0) {
    Op* op = new Op();
    std::unique_lock<std::mutex> lk(mu_);
    op->id = next_op_++;
    op->fn = fn;
    op->ctx = ctx;
    op->priority = priority;
    op->lane = (lane >= 0 && lane < static_cast<int>(ready_.size()))
                   ? lane : 0;
    op->always_run = always_run;
    op->const_vars.assign(cvars, cvars + ncon);
    op->mutable_vars.assign(mvars, mvars + nmut);
    op->missing.store(ncon + nmut);
    ++inflight_;
    if (ncon + nmut == 0) {
      Ready(op);
    } else {
      for (int i = 0; i < ncon; ++i)
        vars_[cvars[i]]->waiters.emplace_back(op, false);
      for (int i = 0; i < nmut; ++i)
        vars_[mvars[i]]->waiters.emplace_back(op, true);
      for (int i = 0; i < ncon; ++i) Grant(vars_[cvars[i]]);
      for (int i = 0; i < nmut; ++i) Grant(vars_[mvars[i]]);
    }
    return op->id;
  }

  // blocks until every op that reads or writes `var` (pushed so far) is
  // done; returns the id of the op that poisoned the var, or -1
  int64_t WaitForVar(int64_t var) {
    std::mutex m;
    std::condition_variable c;
    bool done = false;
    struct Sync {
      std::mutex* m;
      std::condition_variable* c;
      bool* done;
    } sync{&m, &c, &done};
    auto cb = [](void* p) -> int {
      Sync* s = static_cast<Sync*>(p);
      std::unique_lock<std::mutex> lk(*s->m);
      *s->done = true;
      s->c->notify_all();
      return 0;
    };
    int64_t v[1] = {var};
    Push(cb, &sync, v, 1, nullptr, 0, 1 << 20, /*always_run=*/true);
    {
      std::unique_lock<std::mutex> lk(m);
      c.wait(lk, [&] { return done; });
    }
    std::unique_lock<std::mutex> lk(mu_);
    Var* vp = vars_[var];
    return vp->has_error ? vp->error_op : -1;
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(mu_);
    all_done_.wait(lk, [&] { return inflight_ == 0; });
  }

  uint64_t Version(int64_t var) {
    std::unique_lock<std::mutex> lk(mu_);
    return vars_[var]->version;
  }

 private:
  // grant queue heads under mu_
  void Grant(Var* v) {
    while (!v->waiters.empty()) {
      Op* op = v->waiters.front().first;
      bool w = v->waiters.front().second;
      if (w) {
        if (v->active_readers == 0 && !v->active_writer) {
          v->active_writer = true;
          v->waiters.pop_front();
          if (op->missing.fetch_sub(1) == 1) Ready(op);
        }
        break;  // a write (granted or not) blocks everything behind it
      }
      if (v->active_writer) break;
      v->active_readers++;
      v->waiters.pop_front();
      if (op->missing.fetch_sub(1) == 1) Ready(op);
    }
  }

  void Ready(Op* op) {  // under mu_
    ready_[op->lane].push(op);
    lane_cv_[op->lane].notify_one();
  }

  void WorkerLoop(int lane) {
    for (;;) {
      Op* op;
      {
        std::unique_lock<std::mutex> lk(mu_);
        lane_cv_[lane].wait(
            lk, [&] { return shutdown_ || !ready_[lane].empty(); });
        if (shutdown_ && ready_[lane].empty()) return;
        op = ready_[lane].top();
        ready_[lane].pop();
        // poisoned inputs? skip execution, propagate to outputs
        bool poisoned = false;
        int64_t src = -1;
        for (int64_t vid : op->const_vars)
          if (vars_[vid]->has_error) { poisoned = true;
            src = vars_[vid]->error_op; break; }
        if (!poisoned)
          for (int64_t vid : op->mutable_vars)
            if (vars_[vid]->has_error) { poisoned = true;
              src = vars_[vid]->error_op; break; }
        if (poisoned && !op->always_run) {
          Complete(op, true, src);
          continue;
        }
      }
      int rc = op->fn(op->ctx);  // run WITHOUT the lock
      {
        std::unique_lock<std::mutex> lk(mu_);
        Complete(op, rc != 0, op->id);
      }
    }
  }

  void Complete(Op* op, bool failed, int64_t err_src) {  // under mu_
    for (int64_t vid : op->const_vars) {
      Var* v = vars_[vid];
      v->active_readers--;
      Grant(v);
    }
    for (int64_t vid : op->mutable_vars) {
      Var* v = vars_[vid];
      v->active_writer = false;
      v->version++;
      if (failed && !v->has_error) {
        v->has_error = true;
        v->error_op = err_src;
      }
      Grant(v);
    }
    delete op;
    if (--inflight_ == 0) all_done_.notify_all();
  }

  std::mutex mu_;
  std::vector<std::condition_variable> lane_cv_;
  std::condition_variable all_done_;
  std::vector<std::priority_queue<Op*, std::vector<Op*>, OpCmp>> ready_;
  std::unordered_map<int64_t, Var*> vars_;
  std::vector<std::thread> workers_;
  int64_t next_var_ = 0;
  int64_t next_op_ = 0;
  bool shutdown_;
  int inflight_;
};

// ------------------------------------------------------- pooled storage --

class PooledStorage {
 public:
  // strategy + cap knobs (reference: pooled_storage_manager.h
  // GPUPooledStorageManager [Round strategy, pow2 rounding with linear
  // cutoff] / GPUPooledRoundedStorageManager, MXNET_GPU_MEM_POOL_TYPE /
  // _RESERVE / _ROUND_LINEAR_CUTOFF — on TPU HBM belongs to PJRT, so
  // the knobs steer THIS host pool)
  enum Strategy { kNaive = 0, kRound = 1, kUnpooled = 2 };

  explicit PooledStorage(int strategy = kNaive,
                         int64_t max_pooled_bytes = -1)
      : strategy_(strategy), max_pooled_bytes_(max_pooled_bytes) {}

  void* Alloc(size_t size) {
    if (strategy_ == kUnpooled) {
      void* p = malloc(size);
      if (!p) return nullptr;
      std::unique_lock<std::mutex> lk(mu_);
      used_bytes_ += size;
      total_allocs_++;
      sizes_[p] = size;
      return p;
    }
    size = RoundUp(size);
    {
      std::unique_lock<std::mutex> lk(mu_);
      auto it = pool_.find(size);
      if (it != pool_.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        pooled_bytes_ -= size;
        used_bytes_ += size;
        sizes_[p] = size;
        return p;
      }
    }
    void* p = malloc(size);
    if (!p) return nullptr;
    std::unique_lock<std::mutex> lk(mu_);
    used_bytes_ += size;
    total_allocs_++;
    sizes_[p] = size;
    return p;
  }

  void Free(void* p) {  // returns to the pool
    std::unique_lock<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it == sizes_.end()) return;
    size_t size = it->second;
    sizes_.erase(it);
    used_bytes_ -= size;
    if (strategy_ == kUnpooled ||
        (max_pooled_bytes_ >= 0 &&
         pooled_bytes_ + size > static_cast<size_t>(max_pooled_bytes_))) {
      free(p);  // over the reserve cap: give it back to the OS
      return;
    }
    pooled_bytes_ += size;
    pool_[size].push_back(p);
  }

  void DirectFree(void* p) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it != sizes_.end()) {
      used_bytes_ -= it->second;
      sizes_.erase(it);
    }
    free(p);
  }

  void ReleaseAll() {
    std::unique_lock<std::mutex> lk(mu_);
    for (auto& kv : pool_)
      for (void* p : kv.second) free(p);
    pool_.clear();
    pooled_bytes_ = 0;
  }

  void Stats(int64_t* out) {
    std::unique_lock<std::mutex> lk(mu_);
    out[0] = static_cast<int64_t>(used_bytes_);
    out[1] = static_cast<int64_t>(pooled_bytes_);
    out[2] = static_cast<int64_t>(total_allocs_);
  }

 private:
  size_t RoundUp(size_t s) const {
    if (strategy_ == kRound) {
      // pow2 rounding above a linear cutoff (GPUPooledRounded semantics)
      const size_t kCutoff = 1u << 14;
      if (s <= kCutoff) return (s + 63) / 64 * 64;
      size_t r = kCutoff;
      while (r < s) r <<= 1;
      return r;
    }
    // kNaive: page-round large, 64B-round small (exact-size buckets)
    const size_t kPage = 4096;
    if (s >= kPage) return (s + kPage - 1) / kPage * kPage;
    size_t r = 64;
    while (r < s) r <<= 1;
    return r;
  }

  const int strategy_;
  const int64_t max_pooled_bytes_;

  std::mutex mu_;
  std::unordered_map<size_t, std::vector<void*>> pool_;
  std::unordered_map<void*, size_t> sizes_;
  size_t used_bytes_ = 0;
  size_t pooled_bytes_ = 0;
  size_t total_allocs_ = 0;
};

}  // namespace

extern "C" {

void* eng_create(int nthreads) { return new Engine(nthreads); }
void eng_destroy(void* h) { delete static_cast<Engine*>(h); }
int64_t eng_new_var(void* h) { return static_cast<Engine*>(h)->NewVar(); }

int64_t eng_push(void* h, Callback fn, void* ctx, const int64_t* cvars,
                 int ncon, const int64_t* mvars, int nmut, int priority) {
  return static_cast<Engine*>(h)->Push(fn, ctx, cvars, ncon, mvars, nmut,
                                       priority);
}

// ThreadedEnginePerDevice analog: nlanes independent worker pools over
// one dependency state; lane selects the pool (0 = compute, 1 = copy/IO)
void* eng_create_lanes(int nthreads, int nlanes) {
  return new Engine(nthreads, nlanes);
}

int64_t eng_push_lane(void* h, Callback fn, void* ctx,
                      const int64_t* cvars, int ncon, const int64_t* mvars,
                      int nmut, int priority, int lane) {
  return static_cast<Engine*>(h)->Push(fn, ctx, cvars, ncon, mvars, nmut,
                                       priority, /*always_run=*/false,
                                       lane);
}

int64_t eng_wait_for_var(void* h, int64_t var) {
  return static_cast<Engine*>(h)->WaitForVar(var);
}

void eng_wait_all(void* h) { static_cast<Engine*>(h)->WaitForAll(); }

uint64_t eng_var_version(void* h, int64_t var) {
  return static_cast<Engine*>(h)->Version(var);
}

void* pool_create() { return new PooledStorage(); }
void* pool_create2(int strategy, int64_t max_pooled_bytes) {
  return new PooledStorage(strategy, max_pooled_bytes);
}
void pool_destroy(void* h) {
  static_cast<PooledStorage*>(h)->ReleaseAll();
  delete static_cast<PooledStorage*>(h);
}
void* pool_alloc(void* h, int64_t size) {
  return static_cast<PooledStorage*>(h)->Alloc(
      static_cast<size_t>(size));
}
void pool_free(void* h, void* p) { static_cast<PooledStorage*>(h)->Free(p); }
void pool_direct_free(void* h, void* p) {
  static_cast<PooledStorage*>(h)->DirectFree(p);
}
void pool_release_all(void* h) {
  static_cast<PooledStorage*>(h)->ReleaseAll();
}
void pool_stats(void* h, int64_t* out) {
  static_cast<PooledStorage*>(h)->Stats(out);
}

}  // extern "C"
