// Flat C ABI over the mxnet_tpu runtime.
//
// Reference: src/c_api/c_api.cc (NDArray entry points, MXImperativeInvoke)
// and src/c_api/c_predict_api.cc (deploy-only predictor). The reference's
// C API fronts a C++ runtime; in this TPU rebuild the runtime is
// Python/JAX, so this library attaches to a live CPython (when loaded
// from a Python process via ctypes) or embeds one (when linked into a
// standalone C/C++ application) and marshals through the pure-Python
// helpers in mxnet_tpu/c_bridge.py. All entry points return 0 on
// success, -1 on failure with the message retrievable via
// MXGetLastError() — the reference's error convention (c_api_error.h).
//
// Build: make c_api (links libpython; see native/Makefile).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "../include/mxnet_tpu/c_api.h"  // keep definitions in ABI lockstep

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

std::string& last_error() {
  thread_local std::string err;
  return err;
}

// Initialize (or attach to) the interpreter exactly once. When this
// library embeds Python itself, the GIL is released right after init so
// every entry point can use the uniform PyGILState_Ensure pattern.
void ensure_python() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

class Gil {
 public:
  Gil() { ensure_python(); state_ = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

int set_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  last_error() = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) last_error() = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return -1;
}

int set_error(const char* msg) {
  last_error() = msg;
  return -1;
}

PyObject* bridge() {  // borrowed (cached) reference, GIL held
  static PyObject* mod = nullptr;
  if (mod == nullptr) mod = PyImport_ImportModule("mxnet_tpu.c_bridge");
  return mod;
}

// call bridge.<fn>(*args); returns new reference or nullptr
PyObject* bridge_call(const char* fn, PyObject* args) {
  PyObject* mod = bridge();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

// per-thread backing store for MXImperativeInvoke output handle arrays
// (valid until the thread's next invoke — the reference's ret_buf
// convention, c_api_ndarray.cc). The stored handles are OWNED here:
// clear_invoke_ret drops the previous invoke's refs so callers must not
// MXNDArrayFree them (and outputs never leak across a long-lived loop).
std::vector<void*>& invoke_ret() {
  thread_local std::vector<void*> ret;
  return ret;
}

void clear_invoke_ret() {  // GIL must be held
  auto& ret = invoke_ret();
  for (void* h : ret) Py_DECREF(reinterpret_cast<PyObject*>(h));
  ret.clear();
}

constexpr int kMaxDim = 8;

}  // namespace

MXTPU_API int MXGetVersion(int* out) {
  *out = 10700;  // tracks the reference's 1.7 line
  return 0;
}

MXTPU_API const char* MXGetLastError() { return last_error().c_str(); }

MXTPU_API int MXNDArrayCreate(const int64_t* shape, int ndim, int dtype,
                              void** out) {
  if (ndim < 0 || ndim > kMaxDim)
    return set_error("MXNDArrayCreate: ndim must be in [0, 8]");
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* args = Py_BuildValue("(Ni)", shp, dtype);
  PyObject* r = bridge_call("nd_create", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;  // ownership transferred to the handle
  return 0;
}

MXTPU_API int MXNDArrayFree(void* handle) {
  if (handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXNDArrayGetShape(void* handle, int* out_ndim,
                                int64_t* out_shape) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = bridge_call("nd_shape", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_ssize_t n = PyTuple_Size(r);
  if (n > kMaxDim) {
    Py_DECREF(r);
    return set_error("ndim exceeds MX_MAX_DIM (8)");
  }
  *out_ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    out_shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(r, i));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetDType(void* handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = bridge_call("nd_dtype", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(void* handle, const void* data,
                                       size_t nbytes) {
  Gil gil;
  PyObject* mem = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      static_cast<Py_ssize_t>(nbytes), PyBUF_READ);
  if (mem == nullptr) return set_py_error();
  PyObject* args = Py_BuildValue("(ON)", handle, mem);
  PyObject* r = bridge_call("nd_from_bytes", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(void* handle, void* data,
                                     size_t nbytes) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = bridge_call("nd_to_bytes", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return set_py_error();
  }
  if (static_cast<size_t>(len) != nbytes) {
    Py_DECREF(r);
    return set_error("MXNDArraySyncCopyToCPU: size mismatch");
  }
  std::memcpy(data, buf, len);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayWaitAll() {
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* r = bridge_call("wait_all", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXImperativeInvoke(const char* op_name, int num_inputs,
                                 void** inputs, int* num_outputs,
                                 void*** outputs, int num_params,
                                 const char** param_keys,
                                 const char** param_vals) {
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* h = reinterpret_cast<PyObject*>(inputs[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(ins, i, h);
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* args = Py_BuildValue("(sNNN)", op_name, ins, keys, vals);
  PyObject* r = bridge_call("invoke", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_ssize_t n = PyList_Size(r);
  clear_invoke_ret();
  auto& ret = invoke_ret();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(r, i);
    Py_INCREF(o);
    ret.push_back(o);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(n);
  *outputs = ret.data();
  return 0;
}

// ------------------------------------------------------------------------
// C predict API (reference: src/c_api/c_predict_api.cc)
// ------------------------------------------------------------------------

MXTPU_API int MXPredCreate(const char* symbol_json, const void* param_bytes,
                           size_t param_size, int dev_type, int dev_id,
                           uint32_t num_input, const char** input_keys,
                           const uint32_t* input_shape_indptr,
                           const int64_t* input_shape_data, void** out) {
  Gil gil;
  PyObject* shapes = PyDict_New();
  for (uint32_t i = 0; i < num_input; ++i) {
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(shp, j - lo, PyLong_FromLongLong(input_shape_data[j]));
    PyObject* k = PyUnicode_FromString(input_keys[i]);
    PyDict_SetItem(shapes, k, shp);
    Py_DECREF(k);
    Py_DECREF(shp);
  }
  PyObject* pbytes =
      PyBytes_FromStringAndSize(static_cast<const char*>(param_bytes),
                                static_cast<Py_ssize_t>(param_size));
  PyObject* mod = bridge();
  if (mod == nullptr) {
    Py_DECREF(shapes);
    Py_XDECREF(pbytes);
    return set_py_error();
  }
  PyObject* cls = PyObject_GetAttrString(mod, "CPredictor");
  if (cls == nullptr) {
    Py_DECREF(shapes);
    Py_XDECREF(pbytes);
    return set_py_error();
  }
  PyObject* args =
      Py_BuildValue("(sNiiN)", symbol_json, pbytes, dev_type, dev_id, shapes);
  PyObject* pred = PyObject_CallObject(cls, args);
  Py_DECREF(cls);
  Py_DECREF(args);
  if (pred == nullptr) return set_py_error();
  *out = pred;
  return 0;
}

MXTPU_API int MXPredSetInput(void* handle, const char* key,
                             const float* data, uint32_t size) {
  Gil gil;
  PyObject* mem = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(data)),
      static_cast<Py_ssize_t>(size) * 4, PyBUF_READ);
  if (mem == nullptr) return set_py_error();
  PyObject* r = PyObject_CallMethod(reinterpret_cast<PyObject*>(handle),
                                    "set_input", "sN", key, mem);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXPredForward(void* handle) {
  Gil gil;
  PyObject* r = PyObject_CallMethod(reinterpret_cast<PyObject*>(handle),
                                    "forward", nullptr);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXPredGetOutputShape(void* handle, uint32_t index,
                                   int* out_ndim, int64_t* out_shape) {
  Gil gil;
  PyObject* r = PyObject_CallMethod(reinterpret_cast<PyObject*>(handle),
                                    "output_shape", "I", index);
  if (r == nullptr) return set_py_error();
  Py_ssize_t n = PyTuple_Size(r);
  if (n > kMaxDim) {
    Py_DECREF(r);
    return set_error("ndim exceeds MX_MAX_DIM (8)");
  }
  *out_ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    out_shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(r, i));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXPredGetOutput(void* handle, uint32_t index, float* data,
                              uint32_t size) {
  Gil gil;
  PyObject* r = PyObject_CallMethod(reinterpret_cast<PyObject*>(handle),
                                    "output_bytes", "I", index);
  if (r == nullptr) return set_py_error();
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return set_py_error();
  }
  if (static_cast<size_t>(len) != static_cast<size_t>(size) * 4) {
    Py_DECREF(r);
    return set_error("MXPredGetOutput: size mismatch");
  }
  std::memcpy(data, buf, len);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXPredFree(void* handle) {
  if (handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

// ------------------------------------------------------------------------
// Symbol API (reference: src/c_api/c_api_symbolic.cc). Handles are
// Python "cells" (1-element lists) so MXSymbolCompose can swap the
// underlying Symbol in place while C keeps one stable pointer.
// ------------------------------------------------------------------------

namespace {

// thread-local string/name-list returns (reference ret_buf convention)
std::string& str_ret() {
  thread_local std::string s;
  return s;
}

std::vector<std::string>& names_store() {
  thread_local std::vector<std::string> v;
  return v;
}

std::vector<const char*>& names_ret() {
  thread_local std::vector<const char*> v;
  return v;
}

int list_to_names(PyObject* r, uint32_t* out_size, const char*** out_array) {
  auto& store = names_store();
  auto& ret = names_ret();
  store.clear();
  ret.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
    if (c == nullptr) return set_py_error();
    store.emplace_back(c);
  }
  for (auto& s : store) ret.push_back(s.c_str());
  *out_size = static_cast<uint32_t>(n);
  *out_array = ret.data();
  return 0;
}

}  // namespace

MXTPU_API int MXSymbolCreateVariable(const char* name, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* r = bridge_call("sym_var", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolCreateAtomicSymbol(const char* op_name,
                                         uint32_t num_param,
                                         const char** keys,
                                         const char** vals, void** out) {
  Gil gil;
  PyObject* k = PyList_New(num_param);
  PyObject* v = PyList_New(num_param);
  for (uint32_t i = 0; i < num_param; ++i) {
    PyList_SET_ITEM(k, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(v, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* args = Py_BuildValue("(sNN)", op_name, k, v);
  PyObject* r = bridge_call("sym_create_atomic", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolCompose(void* sym, const char* name, uint32_t num_args,
                              const char** keys, void** args_handles) {
  Gil gil;
  PyObject* keylist;
  if (keys == nullptr) {
    keylist = Py_None;
    Py_INCREF(Py_None);
  } else {
    keylist = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i)
      PyList_SET_ITEM(keylist, i, PyUnicode_FromString(keys[i]));
  }
  PyObject* cells = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyObject* h = reinterpret_cast<PyObject*>(args_handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(cells, i, h);
  }
  PyObject* args = Py_BuildValue("(OsNN)", sym, name ? name : "", keylist,
                                 cells);
  PyObject* r = bridge_call("sym_compose", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolCreateFromJSON(const char* json, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* r = bridge_call("sym_from_json", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolSaveToJSON(void* sym, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", sym);
  PyObject* r = bridge_call("sym_to_json", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  const char* c = PyUnicode_AsUTF8(r);
  if (c == nullptr) {
    Py_DECREF(r);
    return set_py_error();
  }
  str_ret() = c;
  Py_DECREF(r);
  *out_json = str_ret().c_str();
  return 0;
}

namespace {

int symbol_list(void* sym, const char* kind, uint32_t* out_size,
                const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", sym, kind);
  PyObject* r = bridge_call("sym_list", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  int rc = list_to_names(r, out_size, out_array);
  Py_DECREF(r);
  return rc;
}

}  // namespace

MXTPU_API int MXSymbolListArguments(void* sym, uint32_t* out_size,
                                    const char*** out_array) {
  return symbol_list(sym, "arguments", out_size, out_array);
}

MXTPU_API int MXSymbolListAuxiliaryStates(void* sym, uint32_t* out_size,
                                          const char*** out_array) {
  return symbol_list(sym, "aux", out_size, out_array);
}

MXTPU_API int MXSymbolListOutputs(void* sym, uint32_t* out_size,
                                  const char*** out_array) {
  return symbol_list(sym, "outputs", out_size, out_array);
}

MXTPU_API int MXSymbolFree(void* sym) {
  if (sym == nullptr) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(sym));
  return 0;
}

// ------------------------------------------------------------------------
// Executor API (reference: src/c_api/c_api_executor.cc:189
// MXExecutorSimpleBindEx). One jitted XLA computation per bind.
// ------------------------------------------------------------------------

MXTPU_API int MXExecutorSimpleBind(void* sym, const char* grad_req,
                                   uint32_t num_input,
                                   const char** input_keys,
                                   const uint32_t* input_shape_indptr,
                                   const int64_t* input_shape_data,
                                   void** out) {
  Gil gil;
  PyObject* shapes = PyDict_New();
  for (uint32_t i = 0; i < num_input; ++i) {
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(shp, j - lo, PyLong_FromLongLong(input_shape_data[j]));
    PyObject* k = PyUnicode_FromString(input_keys[i]);
    PyDict_SetItem(shapes, k, shp);
    Py_DECREF(k);
    Py_DECREF(shp);
  }
  PyObject* args = Py_BuildValue("(OsN)", sym, grad_req, shapes);
  PyObject* r = bridge_call("exec_simple_bind", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXExecutorArgArray(void* exec, const char* kind,
                                 const char* name, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", exec, kind, name);
  PyObject* r = bridge_call("exec_array", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;  // new reference owned by the caller handle
  return 0;
}

MXTPU_API int MXExecutorForward(void* exec, int is_train) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", exec, is_train);
  PyObject* r = bridge_call("exec_forward", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorOutputs(void* exec, int* num_outputs,
                                void*** outputs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", exec);
  PyObject* r = bridge_call("exec_outputs", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_ssize_t n = PyList_Size(r);
  clear_invoke_ret();
  auto& ret = invoke_ret();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(r, i);
    Py_INCREF(o);
    ret.push_back(o);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(n);
  *outputs = ret.data();
  return 0;
}

MXTPU_API int MXExecutorBackward(void* exec) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", exec);
  PyObject* r = bridge_call("exec_backward", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorFree(void* exec) {
  if (exec == nullptr) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(exec));
  return 0;
}

// ------------------------------------------------------------------------
// KVStore API (reference: src/c_api/c_api.cc MXKVStore*). Enables the
// reference's training loop from C: init weights, push grads, pull
// updated weights with a server-side optimizer.
// ------------------------------------------------------------------------

namespace {

PyObject* int_keys(uint32_t num, const int* keys) {  // GIL held
  PyObject* k = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SET_ITEM(k, i, PyLong_FromLong(keys[i]));
  return k;
}

PyObject* handle_list(uint32_t num, void** handles) {  // GIL held
  PyObject* v = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i) {
    PyObject* h = reinterpret_cast<PyObject*>(handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(v, i, h);
  }
  return v;
}

}  // namespace

MXTPU_API int MXKVStoreCreate(const char* type, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* r = bridge_call("kv_create", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXKVStoreSetOptimizer(void* kv, const char* opt_name,
                                    uint32_t num_param, const char** keys,
                                    const char** vals) {
  Gil gil;
  PyObject* k = PyList_New(num_param);
  PyObject* v = PyList_New(num_param);
  for (uint32_t i = 0; i < num_param; ++i) {
    PyList_SET_ITEM(k, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(v, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* args = Py_BuildValue("(OsNN)", kv, opt_name, k, v);
  PyObject* r = bridge_call("kv_set_optimizer", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreInit(void* kv, uint32_t num, const int* keys,
                            void** vals) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", kv, int_keys(num, keys),
                                 handle_list(num, vals));
  PyObject* r = bridge_call("kv_init", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStorePush(void* kv, uint32_t num, const int* keys,
                            void** vals, int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONNi)", kv, int_keys(num, keys),
                                 handle_list(num, vals), priority);
  PyObject* r = bridge_call("kv_push", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStorePull(void* kv, uint32_t num, const int* keys,
                            void** outs, int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONNi)", kv, int_keys(num, keys),
                                 handle_list(num, outs), priority);
  PyObject* r = bridge_call("kv_pull", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreFree(void* kv) {
  if (kv == nullptr) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(kv));
  return 0;
}

// ------------------------------------------------------------------------
// Misc surface: predictor reshape, NDArray views, symbol attrs, kvstore
// metadata (reference: c_predict_api.cc MXPredReshape, c_api.cc
// MXNDArrayReshape/Slice, c_api_symbolic.cc attr entry points)
// ------------------------------------------------------------------------

MXTPU_API int MXPredReshape(uint32_t num_input, const char** input_keys,
                            const uint32_t* input_shape_indptr,
                            const int64_t* input_shape_data, void* handle,
                            void** out) {
  Gil gil;
  PyObject* shapes = PyDict_New();
  for (uint32_t i = 0; i < num_input; ++i) {
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(shp, j - lo, PyLong_FromLongLong(input_shape_data[j]));
    PyObject* k = PyUnicode_FromString(input_keys[i]);
    PyDict_SetItem(shapes, k, shp);
    Py_DECREF(k);
    Py_DECREF(shp);
  }
  PyObject* r = PyObject_CallMethod(reinterpret_cast<PyObject*>(handle),
                                    "reshape", "N", shapes);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  // the reference returns a NEW handle; ours reshapes in place, so hand
  // back the same predictor with its refcount bumped
  Py_INCREF(reinterpret_cast<PyObject*>(handle));
  *out = handle;
  return 0;
}

MXTPU_API int MXNDArrayReshape(void* handle, int ndim, const int64_t* shape,
                               void** out) {
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* args = Py_BuildValue("(ON)", handle, shp);
  PyObject* r = bridge_call("nd_reshape", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXNDArraySlice(void* handle, int64_t begin, int64_t end,
                             void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OLL)", handle,
                                 static_cast<long long>(begin),
                                 static_cast<long long>(end));
  PyObject* r = bridge_call("nd_slice", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolGetAttr(void* sym, const char* key,
                              const char** out_value, int* out_success) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", sym, key);
  PyObject* r = bridge_call("sym_get_attr", args);  // (found, value)
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  int found = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 0)));
  const char* c = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 1));
  str_ret() = c ? c : "";
  Py_DECREF(r);
  *out_success = found;  // presence, NOT value-emptiness
  *out_value = str_ret().c_str();
  return 0;
}

MXTPU_API int MXSymbolSetAttr(void* sym, const char* key,
                              const char* value) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", sym, key, value);
  PyObject* r = bridge_call("sym_set_attr", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreGetType(void* kv, const char** out_type) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", kv, "type");
  PyObject* r = bridge_call("kv_meta", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  const char* c = PyUnicode_AsUTF8(r);
  str_ret() = c ? c : "";
  Py_DECREF(r);
  *out_type = str_ret().c_str();
  return 0;
}

namespace {

int kv_meta_int(void* kv, const char* what, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", kv, what);
  PyObject* r = bridge_call("kv_meta", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

}  // namespace

MXTPU_API int MXKVStoreGetRank(void* kv, int* out) {
  return kv_meta_int(kv, "rank", out);
}

MXTPU_API int MXKVStoreGetGroupSize(void* kv, int* out) {
  return kv_meta_int(kv, "num_workers", out);
}

// ------------------------------------------------------------------------
// NDArray file IO (reference: c_api.cc MXNDArraySave/MXNDArrayLoad) —
// completes the C training story: a C frontend can checkpoint and
// restore what it trained.
// ------------------------------------------------------------------------

namespace {

// thread-local handle storage for MXNDArrayLoad results (the reference
// ret_buf convention; handles are OWNED here until the next load)
std::vector<void*>& load_ret() {
  thread_local std::vector<void*> v;
  return v;
}

void clear_load_ret() {  // GIL held
  for (void* h : load_ret()) Py_DECREF(reinterpret_cast<PyObject*>(h));
  load_ret().clear();
}

}  // namespace

MXTPU_API int MXNDArraySave(const char* fname, uint32_t num,
                            void** handles, const char** keys) {
  Gil gil;
  PyObject* ks;
  if (keys == nullptr) {
    ks = Py_None;
    Py_INCREF(Py_None);
  } else {
    ks = PyList_New(num);
    for (uint32_t i = 0; i < num; ++i) {
      PyObject* s = PyUnicode_FromString(keys[i]);
      if (s == nullptr) {  // invalid UTF-8 key: error, not a NULL slot
        Py_DECREF(ks);
        return set_py_error();
      }
      PyList_SET_ITEM(ks, i, s);
    }
  }
  PyObject* args = Py_BuildValue("(sNN)", fname, ks,
                                 handle_list(num, handles));
  PyObject* r = bridge_call("nd_save", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char* fname, uint32_t* out_size,
                            void*** out_arr, uint32_t* out_name_size,
                            const char*** out_names) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* r = bridge_call("nd_load", args);  // (names, arrays)
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  PyObject* names = PyTuple_GET_ITEM(r, 0);
  PyObject* arrays = PyTuple_GET_ITEM(r, 1);
  int rc = list_to_names(names, out_name_size, out_names);
  if (rc != 0) {
    Py_DECREF(r);
    return rc;
  }
  clear_load_ret();
  auto& ret = load_ret();
  Py_ssize_t n = PyList_Size(arrays);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(arrays, i);
    Py_INCREF(o);
    ret.push_back(o);
  }
  Py_DECREF(r);
  *out_size = static_cast<uint32_t>(n);
  *out_arr = ret.data();
  return 0;
}

// ---- data iterators (reference: c_api.cc MXDataIter* family) -----------

MXTPU_API int MXListDataIters(uint32_t* out_size, const char*** out_names) {
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* r = bridge_call("io_list", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  int rc = list_to_names(r, out_size, out_names);
  Py_DECREF(r);
  return rc;
}

MXTPU_API int MXDataIterCreateIter(const char* name, uint32_t num_param,
                                   const char** keys, const char** vals,
                                   void** out) {
  Gil gil;
  PyObject* k = PyList_New(num_param);
  PyObject* v = PyList_New(num_param);
  for (uint32_t i = 0; i < num_param; ++i) {
    PyList_SET_ITEM(k, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(v, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* args = Py_BuildValue("(sNN)", name, k, v);
  PyObject* r = bridge_call("io_create", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;
  return 0;
}

MXTPU_API int MXDataIterFree(void* it) {
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(it));
  return 0;
}

MXTPU_API int MXDataIterNext(void* it, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(it));
  PyObject* r = bridge_call("io_next", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXDataIterBeforeFirst(void* it) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(it));
  PyObject* r = bridge_call("io_before_first", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

namespace {
int io_get(void* it, const char* fn, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(it));
  PyObject* r = bridge_call(fn, args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;  // new NDArray handle owned by the caller
  return 0;
}
}  // namespace

MXTPU_API int MXDataIterGetData(void* it, void** out) {
  return io_get(it, "io_data", out);
}

MXTPU_API int MXDataIterGetLabel(void* it, void** out) {
  return io_get(it, "io_label", out);
}

MXTPU_API int MXDataIterGetPadNum(void* it, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(it));
  PyObject* r = bridge_call("io_pad", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// ------------------------------------------------------------------------
// CachedOp (reference: src/c_api/c_api_ndarray.cc MXCreateCachedOp /
// MXInvokeCachedOpEx — the hybridize engine over the C ABI)
// ------------------------------------------------------------------------

MXTPU_API int MXCreateCachedOp(void* sym, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", sym);
  PyObject* r = bridge_call("cached_op_create", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;  // owned handle
  return 0;
}

MXTPU_API int MXInvokeCachedOp(void* handle, int num_inputs, void** inputs,
                               int* num_outputs, void*** outputs) {
  Gil gil;
  PyObject* ins = handle_list(static_cast<uint32_t>(num_inputs), inputs);
  PyObject* args = Py_BuildValue("(ON)", handle, ins);
  PyObject* r = bridge_call("cached_op_invoke", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_ssize_t n = PyList_Size(r);
  clear_invoke_ret();
  auto& ret = invoke_ret();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(r, i);
    Py_INCREF(o);
    ret.push_back(o);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(n);
  *outputs = ret.data();
  return 0;
}

MXTPU_API int MXFreeCachedOp(void* handle) {
  if (handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

// ------------------------------------------------------------------------
// Autograd (reference: src/c_api/c_api_ndarray.cc:81-143
// MXAutogradSetIsRecording / MXAutogradMarkVariables /
// MXAutogradBackwardEx / MXNDArrayGetGrad)
// ------------------------------------------------------------------------

MXTPU_API int MXAutogradSetIsRecording(int is_recording, int* prev) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_recording);
  PyObject* r = bridge_call("autograd_set_recording", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradSetIsTraining(int is_training, int* prev) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_training);
  PyObject* r = bridge_call("autograd_set_training", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradMarkVariables(uint32_t num_var, void** var_handles,
                                      uint32_t* grad_reqs,
                                      void** grad_handles) {
  Gil gil;
  PyObject* vars = handle_list(num_var, var_handles);
  // grad_req 0 ("null") slots naturally carry NULL grad handles — map
  // to None rather than Py_INCREF(NULL)
  PyObject* grads = PyList_New(num_var);
  for (uint32_t i = 0; i < num_var; ++i) {
    PyObject* h = (grad_handles == nullptr || grad_handles[i] == nullptr)
        ? Py_None
        : reinterpret_cast<PyObject*>(grad_handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(grads, i, h);
  }
  PyObject* reqs = PyList_New(num_var);
  for (uint32_t i = 0; i < num_var; ++i) {
    PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(grad_reqs[i]));
  }
  PyObject* args = Py_BuildValue("(NNN)", vars, reqs, grads);
  PyObject* r = bridge_call("autograd_mark_variables", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradBackward(uint32_t num_output, void** output_handles,
                                 void** head_grad_handles, int retain_graph,
                                 int train_mode) {
  Gil gil;
  PyObject* outs = handle_list(num_output, output_handles);
  PyObject* heads;
  if (head_grad_handles == nullptr) {
    heads = Py_None;
    Py_INCREF(Py_None);
  } else {
    // reference MXAutogradBackwardEx allows per-entry NULL (ones-like
    // seeding for that head) — map NULL to None, never INCREF(NULL)
    heads = PyList_New(num_output);
    for (uint32_t i = 0; i < num_output; ++i) {
      PyObject* h = head_grad_handles[i] == nullptr
          ? Py_None
          : reinterpret_cast<PyObject*>(head_grad_handles[i]);
      Py_INCREF(h);
      PyList_SET_ITEM(heads, i, h);
    }
  }
  PyObject* args = Py_BuildValue("(NNii)", outs, heads, retain_graph,
                                 train_mode);
  PyObject* r = bridge_call("autograd_backward", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetGrad(void* handle, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = bridge_call("nd_get_grad", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;  // new owned handle (caller frees with MXNDArrayFree)
  return 0;
}

// ------------------------------------------------------------------------
// Profiler (reference: src/c_api/c_api_profile.cc)
// ------------------------------------------------------------------------

MXTPU_API int MXSetProcessProfilerConfig(int num_params, const char** keys,
                                         const char** vals) {
  Gil gil;
  PyObject* k = PyList_New(num_params);
  PyObject* v = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(k, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(v, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* args = Py_BuildValue("(NN)", k, v);
  PyObject* r = bridge_call("profiler_config", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSetProcessProfilerState(int state) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", state);
  PyObject* r = bridge_call("profiler_set_state", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXDumpProcessProfile(int finished) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", finished);
  PyObject* r = bridge_call("profiler_dump", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAggregateProfileStatsPrint(const char** out_str, int reset) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", reset);
  PyObject* r = bridge_call("profiler_stats_print", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  thread_local std::string buf;
  const char* c = PyUnicode_AsUTF8(r);
  buf = c ? c : "";
  Py_DECREF(r);
  *out_str = buf.c_str();
  return 0;
}

MXTPU_API int MXRandomSeed(int seed) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* r = bridge_call("random_seed", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

// ------------------------------------------------------------------------
// Operator introspection (reference: c_api.cc MXListAllOpNames,
// MXSymbolGetAtomicSymbolInfo — frontends autogenerate bindings from it)
// ------------------------------------------------------------------------

MXTPU_API int MXListAllOpNames(uint32_t* out_size,
                               const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = bridge_call("list_all_op_names", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  int rc = list_to_names(r, out_size, out_array);
  Py_DECREF(r);
  return rc;
}

namespace {
// op-info buffers (separate from names_store so interleaved name-list
// calls don't clobber an in-flight info result)
struct OpInfoBuf {
  std::string name, doc;
  std::vector<std::string> arg_names, arg_defaults;
  std::vector<const char*> arg_names_c, arg_defaults_c;
};
OpInfoBuf& opinfo_buf() {
  thread_local OpInfoBuf b;
  return b;
}
}  // namespace

MXTPU_API int MXSymbolGetAtomicSymbolInfo(
    const char* op_name, const char** name, const char** description,
    uint32_t* num_args, const char*** arg_names,
    const char*** arg_default_vals) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", op_name);
  PyObject* r = bridge_call("op_info", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  auto& b = opinfo_buf();
  b.arg_names.clear();
  b.arg_defaults.clear();
  b.arg_names_c.clear();
  b.arg_defaults_c.clear();
  const char* nm = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 0));
  const char* doc = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 1));
  b.name = nm ? nm : "";
  b.doc = doc ? doc : "";
  PyObject* an = PyTuple_GET_ITEM(r, 2);
  PyObject* ad = PyTuple_GET_ITEM(r, 3);
  Py_ssize_t n = PyList_Size(an);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* a = PyUnicode_AsUTF8(PyList_GET_ITEM(an, i));
    const char* d = PyUnicode_AsUTF8(PyList_GET_ITEM(ad, i));
    b.arg_names.emplace_back(a ? a : "");
    b.arg_defaults.emplace_back(d ? d : "");
  }
  Py_DECREF(r);
  for (auto& s : b.arg_names) b.arg_names_c.push_back(s.c_str());
  for (auto& s : b.arg_defaults) b.arg_defaults_c.push_back(s.c_str());
  if (name != nullptr) *name = b.name.c_str();
  if (description != nullptr) *description = b.doc.c_str();
  if (num_args != nullptr) *num_args = static_cast<uint32_t>(n);
  if (arg_names != nullptr) *arg_names = b.arg_names_c.data();
  if (arg_default_vals != nullptr)
    *arg_default_vals = b.arg_defaults_c.data();
  return 0;
}

// ------------------------------------------------------------------------
// Shape/type inference over the ABI (reference: c_api_symbolic.cc
// MXSymbolInferShape / MXSymbolInferType). Shapes return via flattened
// thread-local buffers: per-section [count, then per-entry ndim]
// indexing into one int64 data array; -1 ndim = undetermined.
// ------------------------------------------------------------------------

namespace {
struct InferBuf {
  std::vector<int64_t> ndims;   // arg..., out..., aux... (-1 unknown)
  std::vector<int64_t> data;    // concatenated dims
  std::vector<int64_t> section; // [n_args, n_outs, n_aux]
};
InferBuf& infer_buf() {
  thread_local InferBuf b;
  return b;
}

int pack_shapes(PyObject* shapes, InferBuf& b) {  // list of tuple|None
  Py_ssize_t n = PyList_Size(shapes);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* s = PyList_GET_ITEM(shapes, i);
    if (s == Py_None) {
      b.ndims.push_back(-1);
      continue;
    }
    Py_ssize_t nd = PySequence_Size(s);
    b.ndims.push_back(nd);
    for (Py_ssize_t d = 0; d < nd; ++d) {
      PyObject* it = PySequence_GetItem(s, d);
      b.data.push_back(it ? PyLong_AsLongLong(it) : -1);
      Py_XDECREF(it);
    }
  }
  return static_cast<int>(n);
}
}  // namespace

MXTPU_API int MXSymbolInferShape(
    void* sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const int64_t* arg_shape_data,
    uint32_t* out_total, const int64_t** out_ndims,
    const int64_t** out_dims, const int64_t** out_sections) {
  Gil gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* pshapes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t d = lo; d < hi; ++d) {
      PyList_SET_ITEM(shp, d - lo,
                      PyLong_FromLongLong(arg_shape_data[d]));
    }
    PyList_SET_ITEM(pshapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(ONN)", sym, pkeys, pshapes);
  PyObject* r = bridge_call("sym_infer_shape", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  auto& b = infer_buf();
  b.ndims.clear();
  b.data.clear();
  b.section.clear();
  // r = (arg_names, arg_shapes, out_shapes, aux_names, aux_shapes)
  b.section.push_back(pack_shapes(PyTuple_GET_ITEM(r, 1), b));
  b.section.push_back(pack_shapes(PyTuple_GET_ITEM(r, 2), b));
  b.section.push_back(pack_shapes(PyTuple_GET_ITEM(r, 4), b));
  Py_DECREF(r);
  if (out_total != nullptr)
    *out_total = static_cast<uint32_t>(b.ndims.size());
  if (out_ndims != nullptr) *out_ndims = b.ndims.data();
  if (out_dims != nullptr) *out_dims = b.data.data();
  if (out_sections != nullptr) *out_sections = b.section.data();
  return 0;
}

MXTPU_API int MXSymbolInferType(void* sym, uint32_t num_args,
                                const char** keys, const int* arg_types,
                                uint32_t* out_total, const int** out_types,
                                const int64_t** out_sections) {
  Gil gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* ptypes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(ptypes, i, PyLong_FromLong(arg_types[i]));
  }
  PyObject* args = Py_BuildValue("(ONN)", sym, pkeys, ptypes);
  PyObject* r = bridge_call("sym_infer_type", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  thread_local std::vector<int> types;
  thread_local std::vector<int64_t> sections;
  types.clear();
  sections.clear();
  for (int part : {1, 2, 4}) {
    PyObject* lst = PyTuple_GET_ITEM(r, part);
    Py_ssize_t n = PyList_Size(lst);
    sections.push_back(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      types.push_back(
          static_cast<int>(PyLong_AsLong(PyList_GET_ITEM(lst, i))));
    }
  }
  Py_DECREF(r);
  if (out_total != nullptr)
    *out_total = static_cast<uint32_t>(types.size());
  if (out_types != nullptr) *out_types = types.data();
  if (out_sections != nullptr) *out_sections = sections.data();
  return 0;
}

// ------------------------------------------------------------------------
// KVStore tail + NDArray misc (reference: c_api.cc)
// ------------------------------------------------------------------------

MXTPU_API int MXKVStoreBarrier(void* kv) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", kv);
  PyObject* r = bridge_call("kv_barrier", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStorePushPull(void* kv, uint32_t num, const int* keys,
                                void** vals, void** outs, int priority) {
  Gil gil;
  PyObject* pk = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i) {
    PyList_SET_ITEM(pk, i, PyLong_FromLong(keys[i]));
  }
  PyObject* pv = handle_list(num, vals);
  PyObject* po = handle_list(num, outs);
  PyObject* args = Py_BuildValue("(ONNNi)", kv, pk, pv, po, priority);
  PyObject* r = bridge_call("kv_pushpull", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayAt(void* handle, uint32_t idx, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", handle, idx);
  PyObject* r = bridge_call("nd_at", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  *out = r;  // caller frees with MXNDArrayFree
  return 0;
}

MXTPU_API int MXNDArrayGetContext(void* handle, int* out_dev_type,
                                  int* out_dev_id) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = bridge_call("nd_context", args);
  Py_DECREF(args);
  if (r == nullptr) return set_py_error();
  if (out_dev_type != nullptr)
    *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 0)));
  if (out_dev_id != nullptr)
    *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 1)));
  Py_DECREF(r);
  return 0;
}
