// Compiled text-format parsers for the IO subsystem: CSV and LibSVM.
//
// Reference: src/io/iter_csv.cc and src/io/iter_libsvm.cc — the
// reference parses these formats in C++ inside its threaded iterator
// stack; the Python stand-ins (numpy.loadtxt / str.split) pay Python
// object overhead per token. These parsers are GIL-free and
// multithreaded: the file is read once, split into line-aligned chunks,
// and each chunk is parsed by a worker with strtof/strtol; results are
// stitched in order.
//
// C ABI (consumed by mxnet_tpu/io via ctypes):
//   csv_parse(path) -> handle | NULL      csv_free(handle)
//   csv_rows/csv_cols(handle)             csv_data(handle) -> float*
//   svm_parse(path, inline_labels) -> handle | NULL   svm_free(handle)
//   svm_rows/svm_nnz(handle)
//   svm_data/svm_labels -> float*, svm_indices/svm_indptr -> int64*
//   textio_last_error() -> const char* (thread-local)

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <exception>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#define TEXTIO_API extern "C" __attribute__((visibility("default")))

namespace {

std::string& last_error() {
  thread_local std::string err;
  return err;
}

bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) {
    last_error() = std::string("cannot open ") + path;
    return false;
  }
  long n = -1;
  if (std::fseek(f, 0, SEEK_END) == 0) n = std::ftell(f);
  if (n < 0 || std::fseek(f, 0, SEEK_SET) != 0) {
    // directories and unseekable streams land here; a catchable error
    // beats resize((size_t)-1) aborting the host process
    std::fclose(f);
    last_error() = std::string("not a regular readable file: ") + path;
    return false;
  }
  out->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(&(*out)[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  if (got != static_cast<size_t>(n)) {
    last_error() = std::string("short read on ") + path;
    return false;
  }
  return true;
}

// split [0, size) into up to `parts` chunks aligned to '\n'
std::vector<std::pair<size_t, size_t>> line_chunks(const std::string& buf,
                                                   unsigned parts) {
  std::vector<std::pair<size_t, size_t>> out;
  size_t size = buf.size();
  if (size == 0) return out;
  size_t per = std::max<size_t>(size / std::max(1u, parts), 1);
  size_t begin = 0;
  while (begin < size) {
    size_t end = std::min(begin + per, size);
    while (end < size && buf[end] != '\n') ++end;
    if (end < size) ++end;  // include the newline
    out.emplace_back(begin, end);
    begin = end;
  }
  return out;
}

unsigned n_workers() {
  unsigned hw = std::thread::hardware_concurrency();
  return hw ? std::min(hw, 16u) : 4u;
}

struct CsvResult {
  std::vector<float> data;
  int64_t rows = 0;
  int64_t cols = 0;
};

struct SvmResult {
  std::vector<float> data;
  std::vector<int64_t> indices;
  std::vector<int64_t> indptr;  // rows+1
  std::vector<float> labels;
  int64_t rows = 0;
};

bool parse_csv_chunk(const char* p, const char* end,
                     std::vector<float>* vals, std::vector<int64_t>* rows,
                     std::string* err) {
  // rows gets the running column count per line for shape validation
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;
    const char* next_line = line_end;
    // '#' starts a comment (numpy.loadtxt-compatible; whole-line or
    // trailing) — the line is truncated there
    const char* hash = static_cast<const char*>(
        std::memchr(p, '#', static_cast<size_t>(line_end - p)));
    if (hash != nullptr) line_end = hash;
    bool blank = true;
    for (const char* q = p; q < line_end; ++q)
      if (!std::isspace(static_cast<unsigned char>(*q))) { blank = false; break; }
    if (!blank) {
      int64_t ncol = 0;
      while (p < line_end) {
        char* next = nullptr;
        float v = std::strtof(p, &next);
        if (next == p) {
          *err = "malformed CSV number near '" +
                 std::string(p, std::min<size_t>(16, line_end - p)) + "'";
          return false;
        }
        vals->push_back(v);
        ++ncol;
        p = next;
        while (p < line_end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (p < line_end && *p == ',') {
          ++p;
          while (p < line_end && (*p == ' ' || *p == '\t')) ++p;
        }
      }
      rows->push_back(ncol);
    }
    p = (next_line < end) ? next_line + 1 : end;
  }
  return true;
}

bool parse_svm_chunk(const char* p, const char* end, bool inline_labels,
                     SvmResult* out, std::string* err) {
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;
    while (p < line_end && std::isspace(static_cast<unsigned char>(*p))) ++p;
    if (p < line_end) {
      if (inline_labels) {
        char* next = nullptr;
        float lab = std::strtof(p, &next);
        if (next == p) {
          *err = "malformed libsvm label";
          return false;
        }
        out->labels.push_back(lab);
        p = next;
      }
      while (p < line_end) {
        while (p < line_end &&
               std::isspace(static_cast<unsigned char>(*p))) ++p;
        if (p >= line_end || *p == '#') break;  // trailing comment
        char* next = nullptr;
        long idx = std::strtol(p, &next, 10);
        if (next == p || next >= line_end || *next != ':') {
          *err = "malformed libsvm token near '" +
                 std::string(p, std::min<size_t>(16, line_end - p)) + "'";
          return false;
        }
        p = next + 1;
        float v = std::strtof(p, &next);
        if (next == p) {
          *err = "malformed libsvm value";
          return false;
        }
        out->indices.push_back(idx);
        out->data.push_back(v);
        p = next;
      }
      out->indptr.push_back(static_cast<int64_t>(out->indices.size()));
      ++out->rows;
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return true;
}

}  // namespace

TEXTIO_API const char* textio_last_error() { return last_error().c_str(); }

namespace {

void* csv_parse_impl(const char* path) {
  std::string buf;
  if (!read_file(path, &buf)) return nullptr;
  auto chunks = line_chunks(buf, n_workers());
  std::vector<std::vector<float>> vals(chunks.size());
  std::vector<std::vector<int64_t>> rows(chunks.size());
  std::vector<std::string> errs(chunks.size());
  std::vector<char> ok(chunks.size(), 1);
  std::vector<std::thread> threads;
  for (size_t i = 0; i < chunks.size(); ++i) {
    threads.emplace_back([&, i] {
      ok[i] = parse_csv_chunk(buf.data() + chunks[i].first,
                              buf.data() + chunks[i].second, &vals[i],
                              &rows[i], &errs[i]);
    });
  }
  for (auto& t : threads) t.join();
  for (size_t i = 0; i < chunks.size(); ++i) {
    if (!ok[i]) {
      last_error() = errs[i];
      return nullptr;
    }
  }
  auto* res = new CsvResult();
  for (auto& r : rows) {
    for (int64_t ncol : r) {
      if (res->cols == 0) res->cols = ncol;
      if (ncol != res->cols) {
        last_error() = "ragged CSV: row with " + std::to_string(ncol) +
                       " columns, expected " + std::to_string(res->cols);
        delete res;
        return nullptr;
      }
      ++res->rows;
    }
  }
  size_t total = 0;
  for (auto& v : vals) total += v.size();
  res->data.reserve(total);
  for (auto& v : vals)
    res->data.insert(res->data.end(), v.begin(), v.end());
  return res;
}

}  // namespace

TEXTIO_API void* csv_parse(const char* path) {
  // no C++ exception may cross the C ABI (std::terminate otherwise)
  try {
    return csv_parse_impl(path);
  } catch (const std::exception& e) {
    last_error() = e.what();
    return nullptr;
  }
}

TEXTIO_API int64_t csv_rows(void* h) {
  return static_cast<CsvResult*>(h)->rows;
}
TEXTIO_API int64_t csv_cols(void* h) {
  return static_cast<CsvResult*>(h)->cols;
}
TEXTIO_API const float* csv_data(void* h) {
  return static_cast<CsvResult*>(h)->data.data();
}
TEXTIO_API void csv_free(void* h) { delete static_cast<CsvResult*>(h); }

namespace {

void* svm_parse_impl(const char* path, int inline_labels) {
  std::string buf;
  if (!read_file(path, &buf)) return nullptr;
  auto chunks = line_chunks(buf, n_workers());
  std::vector<SvmResult> parts(chunks.size());
  std::vector<std::string> errs(chunks.size());
  std::vector<char> ok(chunks.size(), 1);
  std::vector<std::thread> threads;
  for (size_t i = 0; i < chunks.size(); ++i) {
    threads.emplace_back([&, i] {
      ok[i] = parse_svm_chunk(buf.data() + chunks[i].first,
                              buf.data() + chunks[i].second,
                              inline_labels != 0, &parts[i], &errs[i]);
    });
  }
  for (auto& t : threads) t.join();
  for (size_t i = 0; i < chunks.size(); ++i) {
    if (!ok[i]) {
      last_error() = errs[i];
      return nullptr;
    }
  }
  auto* res = new SvmResult();
  res->indptr.push_back(0);
  for (auto& p : parts) {
    int64_t base = static_cast<int64_t>(res->indices.size());
    res->data.insert(res->data.end(), p.data.begin(), p.data.end());
    res->indices.insert(res->indices.end(), p.indices.begin(),
                        p.indices.end());
    res->labels.insert(res->labels.end(), p.labels.begin(),
                       p.labels.end());
    for (int64_t ip : p.indptr) res->indptr.push_back(base + ip);
    res->rows += p.rows;
  }
  return res;
}

}  // namespace

TEXTIO_API void* svm_parse(const char* path, int inline_labels) {
  try {
    return svm_parse_impl(path, inline_labels);
  } catch (const std::exception& e) {
    last_error() = e.what();
    return nullptr;
  }
}

TEXTIO_API int64_t svm_rows(void* h) {
  return static_cast<SvmResult*>(h)->rows;
}
TEXTIO_API int64_t svm_nnz(void* h) {
  return static_cast<int64_t>(static_cast<SvmResult*>(h)->data.size());
}
TEXTIO_API const float* svm_data(void* h) {
  return static_cast<SvmResult*>(h)->data.data();
}
TEXTIO_API const int64_t* svm_indices(void* h) {
  return static_cast<SvmResult*>(h)->indices.data();
}
TEXTIO_API const int64_t* svm_indptr(void* h) {
  return static_cast<SvmResult*>(h)->indptr.data();
}
TEXTIO_API const float* svm_labels(void* h) {
  return static_cast<SvmResult*>(h)->labels.data();
}
TEXTIO_API void svm_free(void* h) { delete static_cast<SvmResult*>(h); }
