// Native recordio + image pipeline for mxnet_tpu.
//
// TPU-native equivalent of the reference's C++ input stack:
//   - dmlc recordio frame parsing (reference interface dmlc/recordio.h,
//     consumed by src/io/iter_image_recordio_2.cc ParseChunk)
//   - OMP-parallel JPEG decode + augment (reference
//     src/io/iter_image_recordio_2.cc:79-146) — here a std::thread pool
//     decoding via libjpeg with resize-short-edge + crop + mirror fused
//     into the decode loop, filling a caller-owned batch buffer without
//     holding the Python GIL.
//
// Exposed as a flat C ABI loaded via ctypes (the reference exposes its
// pipeline through the C API iterator handles, include/mxnet/c_api.h).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <csetjmp>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Reader {
  FILE* fp = nullptr;
  std::vector<uint8_t> buf;   // assembled logical record
  std::string err;
};

// ---------------------------------------------------------------- frames --

bool read_exact(FILE* fp, void* dst, size_t n) {
  return fread(dst, 1, n, fp) == n;
}

// Returns 1 ok, 0 eof, -1 error. Assembles split records (cflag 1/2/3)
// re-inserting the magic word between parts, mirroring dmlc-core's
// RecordIOReader::NextRecord.
int next_record(Reader* r) {
  r->buf.clear();
  bool in_split = false;
  for (;;) {
    uint32_t magic, lrec;
    if (!read_exact(r->fp, &magic, 4)) return in_split ? -1 : 0;
    if (magic != kMagic) { r->err = "bad magic"; return -1; }
    if (!read_exact(r->fp, &lrec, 4)) { r->err = "truncated"; return -1; }
    uint32_t cflag = lrec >> 29, len = lrec & ((1u << 29) - 1);
    size_t off = r->buf.size();
    if (in_split) {
      const uint8_t* m = reinterpret_cast<const uint8_t*>(&kMagic);
      r->buf.insert(r->buf.end(), m, m + 4);
      off = r->buf.size();
    }
    r->buf.resize(off + len);
    if (len && !read_exact(r->fp, r->buf.data() + off, len)) {
      r->err = "truncated payload"; return -1;
    }
    uint32_t pad = (4 - (len & 3u)) & 3u;
    if (pad) { uint8_t tmp[4]; if (!read_exact(r->fp, tmp, pad)) return -1; }
    if (cflag == 0) return 1;
    if (cflag == 1) { in_split = true; continue; }
    if (cflag == 2) continue;
    if (cflag == 3) return 1;
  }
}

// ------------------------------------------------------------ jpeg decode --

struct JErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  JErr* e = reinterpret_cast<JErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decode JPEG to RGB, resize shorter edge to `resize_short` (bilinear,
// 0 = no resize), then crop H×W, optional horizontal mirror. cy/cx: -1 =
// center; else a fraction of the free space in units of 1/10000 (the
// caller can't know post-resize dims, so random crops are expressed
// fractionally). Output HWC uint8 into out (H*W*3). Returns 0 ok.
int decode_one(const uint8_t* data, size_t len, int H, int W,
               int resize_short, int cy, int cx, int mirror,
               uint8_t* out) {
  // buffers are declared BEFORE setjmp: a longjmp from the libjpeg error
  // handler lands back here and we return normally, so their destructors
  // run (declaring them after the setjmp would skip destruction — UB+leak)
  std::vector<uint8_t> img;
  std::vector<uint8_t> resized;
  jpeg_decompress_struct cinfo;
  JErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) { jpeg_destroy_decompress(&cinfo); return -1; }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo); return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  // use libjpeg's cheap power-of-2 DCT scaling to get close to the target
  if (resize_short > 0) {
    int short_edge = cinfo.image_height < cinfo.image_width
                         ? cinfo.image_height : cinfo.image_width;
    int denom = 1;
    while (denom < 8 && short_edge / (denom * 2) >= resize_short) denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  int sw = cinfo.output_width, sh = cinfo.output_height;
  img.resize(static_cast<size_t>(sw) * sh * 3);
  JSAMPROW row;
  while (cinfo.output_scanline < cinfo.output_height) {
    row = img.data() + static_cast<size_t>(cinfo.output_scanline) * sw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // bilinear resize so the short edge == resize_short (or to cover crop)
  int tw = sw, th = sh;
  if (resize_short > 0) {
    if (sh < sw) { th = resize_short; tw = (int)((int64_t)sw * resize_short / sh); }
    else        { tw = resize_short; th = (int)((int64_t)sh * resize_short / sw); }
  }
  if (tw < W) { th = (int)((int64_t)th * W / tw); tw = W; }
  if (th < H) { tw = (int)((int64_t)tw * H / th); th = H; }
  const uint8_t* src = img.data();
  if (tw != sw || th != sh) {
    resized.resize(static_cast<size_t>(tw) * th * 3);
    for (int y = 0; y < th; ++y) {
      float fy = (y + 0.5f) * sh / th - 0.5f;
      int y0 = fy < 0 ? 0 : (int)fy;
      int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
      float wy = fy - y0; if (wy < 0) wy = 0;
      for (int x = 0; x < tw; ++x) {
        float fx = (x + 0.5f) * sw / tw - 0.5f;
        int x0 = fx < 0 ? 0 : (int)fx;
        int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
        float wx = fx - x0; if (wx < 0) wx = 0;
        for (int c = 0; c < 3; ++c) {
          float v00 = img[((size_t)y0 * sw + x0) * 3 + c];
          float v01 = img[((size_t)y0 * sw + x1) * 3 + c];
          float v10 = img[((size_t)y1 * sw + x0) * 3 + c];
          float v11 = img[((size_t)y1 * sw + x1) * 3 + c];
          float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx;
          resized[((size_t)y * tw + x) * 3 + c] =
              (uint8_t)(v + 0.5f);
        }
      }
    }
    src = resized.data();
    sw = tw; sh = th;
  }
  if (cy < 0) cy = (sh - H) / 2;
  else cy = (int)((int64_t)cy * (sh - H) / 10000);
  if (cx < 0) cx = (sw - W) / 2;
  else cx = (int)((int64_t)cx * (sw - W) / 10000);
  if (cy + H > sh) cy = sh - H;
  if (cx + W > sw) cx = sw - W;
  if (cy < 0 || cx < 0) return -2;  // image smaller than crop
  for (int y = 0; y < H; ++y) {
    const uint8_t* srow = src + (((size_t)(cy + y)) * sw + cx) * 3;
    uint8_t* drow = out + (size_t)y * W * 3;
    if (!mirror) {
      memcpy(drow, srow, (size_t)W * 3);
    } else {
      for (int x = 0; x < W; ++x) {
        const uint8_t* s3 = srow + (size_t)(W - 1 - x) * 3;
        drow[x * 3] = s3[0]; drow[x * 3 + 1] = s3[1]; drow[x * 3 + 2] = s3[2];
      }
    }
  }
  return 0;
}

}  // namespace

extern "C" {

void* rio_open(const char* path) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  Reader* r = new Reader();
  r->fp = fp;
  return r;
}

void rio_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r) { if (r->fp) fclose(r->fp); delete r; }
}

void rio_seek(void* h, long pos) {
  Reader* r = static_cast<Reader*>(h);
  fseek(r->fp, pos, SEEK_SET);
}

long rio_tell(void* h) {
  Reader* r = static_cast<Reader*>(h);
  return ftell(r->fp);
}

// Returns payload length (>=0) with *out pointing at an internal buffer
// valid until the next call; -1 at EOF; -2 on format error.
long rio_next(void* h, const uint8_t** out) {
  Reader* r = static_cast<Reader*>(h);
  int rc = next_record(r);
  if (rc == 0) return -1;
  if (rc < 0) return -2;
  *out = r->buf.data();
  return static_cast<long>(r->buf.size());
}

int decode_jpeg(const uint8_t* data, long len, int H, int W,
                int resize_short, int cy, int cx, int mirror, uint8_t* out) {
  return decode_one(data, static_cast<size_t>(len), H, W, resize_short,
                    cy, cx, mirror, out);
}

// Parallel batch decode: n images, offsets[i]/lengths[i] into blob, each
// decoded+cropped into out[i] (H*W*3, HWC uint8). crops: per-image
// (cy, cx, mirror) triples, cy/cx = -1 for center. Returns count of
// failures (failed slots are zero-filled).
int decode_batch(const uint8_t* blob, const int64_t* offsets,
                 const int64_t* lengths, int n, int H, int W,
                 int resize_short, const int32_t* crops, int nthreads,
                 uint8_t* out) {
  if (nthreads < 1) nthreads = 1;
  std::vector<int> fails(nthreads, 0);
  size_t stride = static_cast<size_t>(H) * W * 3;
  auto work = [&](int tid) {
    for (int i = tid; i < n; i += nthreads) {
      uint8_t* dst = out + stride * i;
      int rc = decode_one(blob + offsets[i],
                          static_cast<size_t>(lengths[i]), H, W,
                          resize_short, crops[i * 3], crops[i * 3 + 1],
                          crops[i * 3 + 2], dst);
      if (rc != 0) { memset(dst, 0, stride); fails[tid]++; }
    }
  };
  if (nthreads == 1) {
    work(0);
  } else {
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) ts.emplace_back(work, t);
    for (auto& t : ts) t.join();
  }
  int total = 0;
  for (int f : fails) total += f;
  return total;
}

}  // extern "C"
