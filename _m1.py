print("start", flush=True)
import mxnet_tpu as mx
print("import ok", flush=True)
