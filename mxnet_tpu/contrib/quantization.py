"""Post-training int8 quantization driver (reference:
python/mxnet/contrib/quantization.py:923 quantize_model/quantize_net over
the quantize_graph_pass.cc graph rewrite).

Gluon flow: `quantize_net(net, calib_data=...)` runs calibration batches
to collect per-layer activation ranges (naive min/max or KL-entropy), then
swaps Dense/Conv2D children for int8-computing wrappers. The int8 matmul
accumulates in int32 on the MXU (jax lax.dot preferred_element_type) and
dequantizes with the calibrated scales — the TPU analog of the reference's
MKLDNN/cuDNN int8 kernels.
"""
from __future__ import annotations

import numpy as onp

__all__ = ["quantize_net", "quantize_net_graph", "QuantizedDense",
           "QuantizedConv2D", "calib_entropy", "quantize_symbol",
           "quantize_model"]


def calib_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence threshold selection (reference: quantization.py
    _get_optimal_threshold / calibrate.cc). Returns the |threshold| that
    minimizes KL(P||Q) between the fp32 histogram and its int8 image."""
    hist = onp.asarray(hist, dtype=onp.float64)
    nbins = len(hist)
    best_kl, best_t = None, hist_edges[-1]
    # only consider thresholds that keep >=99% of the mass in range:
    # mass piled into the clip bin is exactly representable by Q, so the
    # raw KL objective would otherwise reward absurdly tight clips
    cum = hist.cumsum() / max(hist.sum(), 1e-12)
    start = int(onp.searchsorted(cum, 0.99)) + 1
    start = max(start, num_quantized_bins // 2)
    # evaluate at most ~128 candidate thresholds: the KL(i) curve over a
    # 2048-bin histogram is smooth at this granularity, and the exhaustive
    # sweep is an O(nbins * num_quantized_bins) python loop per tensor
    stride = max(1, (nbins + 1 - start) // 128)
    for i in range(start, nbins + 1, stride):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()  # clip outliers into the edge bin
        # quantize p into num_quantized_bins then expand back
        factor = i / num_quantized_bins
        q = onp.zeros(i)
        for b in range(num_quantized_bins):
            lo = int(onp.floor(b * factor))
            hi = max(int(onp.ceil((b + 1) * factor)), lo + 1)
            mass = p[lo:hi].sum()
            nz = (p[lo:hi] > 0).sum()
            if nz:
                q[lo:hi] = onp.where(p[lo:hi] > 0, mass / nz, 0)
        pm = p / max(p.sum(), 1e-12)
        qm = q / max(q.sum(), 1e-12)
        nzmask = pm > 0
        kl = float((pm[nzmask] * onp.log(
            pm[nzmask] / onp.maximum(qm[nzmask], 1e-12))).sum())
        if best_kl is None or kl < best_kl:
            best_kl, best_t = kl, hist_edges[i]
    return best_t


class _QuantizedBase:
    def _quant_weight(self, w):
        import jax.numpy as jnp

        amax = float(onp.abs(w.asnumpy()).max())
        scale = 127.0 / max(amax, 1e-20)
        wq = jnp.clip(jnp.rint(w.data * scale), -127, 127).astype(jnp.int8)
        return wq, amax


class QuantizedDense(_QuantizedBase):
    """int8 x int8 → int32 matmul + dequant (reference:
    quantized_fully_connected.cc)."""

    def __init__(self, dense, act_range):
        self._units = dense._units if hasattr(dense, "_units") else None
        self._wq, self._wmax = self._quant_weight(dense.weight.data())
        self._bias = dense.bias.data().data if dense.bias is not None \
            else None
        self._act = dense.act if getattr(dense, "act", None) else None
        self._amax = max(abs(act_range[0]), abs(act_range[1]))
        self._flatten = getattr(dense, "_flatten", True)

    def __call__(self, x):
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray import NDArray

        xd = x.data
        if self._flatten and xd.ndim > 2:
            xd = xd.reshape(xd.shape[0], -1)
        xscale = 127.0 / max(self._amax, 1e-20)
        xq = jnp.clip(jnp.rint(xd * xscale), -127, 127).astype(jnp.int8)
        acc = lax.dot(xq, self._wq.T,
                      preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (
            (self._amax / 127.0) * (self._wmax / 127.0))
        if self._bias is not None:
            out = out + self._bias
        res = NDArray(out.astype(x.data.dtype))
        if self._act is not None:
            res = self._act(res)
        return res


class QuantizedConv2D(_QuantizedBase):
    """int8 conv accumulating int32 (reference: quantized_conv.cc)."""

    def __init__(self, conv, act_range):
        self._wq, self._wmax = self._quant_weight(conv.weight.data())
        self._bias = conv.bias.data().data if conv.bias is not None \
            else None
        self._act = getattr(conv, "act", None)
        self._amax = max(abs(act_range[0]), abs(act_range[1]))
        self._strides = conv._stride
        self._padding = conv._pad
        self._groups = conv._groups
        self._dilation = conv._dilate

    def __call__(self, x):
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray import NDArray

        xscale = 127.0 / max(self._amax, 1e-20)
        xq = jnp.clip(jnp.rint(x.data * xscale), -127, 127).astype(jnp.int8)
        pad = [(int(p), int(p)) for p in self._padding]
        acc = lax.conv_general_dilated(
            xq, self._wq, window_strides=tuple(int(s) for s in
                                               self._strides),
            padding=pad, feature_group_count=self._groups,
            rhs_dilation=tuple(int(d) for d in self._dilation),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (
            (self._amax / 127.0) * (self._wmax / 127.0))
        if self._bias is not None:
            out = out + self._bias.reshape(1, -1, 1, 1)
        res = NDArray(out.astype(x.data.dtype))
        if self._act is not None:
            res = self._act(res)
        return res


def quantize_net(network, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=None,
                 num_calib_batches=None, logger=None):
    """Calibrate + swap Dense/Conv2D for int8 versions, in place.

    Reference: contrib/quantization.py quantize_net (calib_mode 'naive' =
    min/max, 'entropy' = KL threshold; layer exclusion by name).
    """
    from ..gluon import nn
    from .. import autograd

    exclude = set(exclude_layers or [])

    # deactivate any hybridization: calibration taps must see eager
    # NDArrays, and a stale CachedOp would keep replaying the fp32 graph
    # after the swap
    def dehybridize(block):
        if hasattr(block, "_cached_op"):
            block._cached_op = None
        if hasattr(block, "_active"):
            block._active = False
        for child in getattr(block, "_children", {}).values():
            dehybridize(child)

    dehybridize(network)

    targets = {}  # (id(parent), child_name) -> [parent, name, child]

    def find(block):
        for name, child in list(block._children.items()):
            if isinstance(child, (nn.Dense, nn.Conv2D)) and \
                    child.name not in exclude:
                if isinstance(child, nn.Conv2D) and \
                        child._layout != "NCHW":
                    continue  # only NCHW is wired for int8 conv
                targets[(id(block), name)] = [block, name, child]
            find(child)

    find(network)
    if not targets:
        return network

    # calibration taps: O(1) running min/max + bounded sample reservoir
    # for the entropy histogram (full activations are never retained)
    stats = {key: {"min": onp.inf, "max": -onp.inf, "samples": []}
             for key in targets}
    _CAP = 16384  # abs-value samples kept per layer per batch
    # one persistent RNG per quantize_net call: a fresh RandomState(0)
    # per batch would resample the same flattened indices every batch for
    # equal-size activations, biasing the histogram toward fixed positions
    _rng = onp.random.RandomState(0)
    hooks = []
    for key, (blk, name, child) in targets.items():
        orig = child.forward

        def tapped(x, *a, _orig=orig, _key=key, **kw):
            v = onp.asarray(x.asnumpy(), dtype=onp.float32).reshape(-1)
            st = stats[_key]
            st["min"] = min(st["min"], float(v.min()))
            st["max"] = max(st["max"], float(v.max()))
            if calib_mode == "entropy":
                av = onp.abs(v)
                if av.size > _CAP:
                    av = av[_rng.choice(av.size, _CAP, replace=False)]
                st["samples"].append(av)
            return _orig(x, *a, **kw)

        child.forward = tapped
        hooks.append((child, orig))
    try:
        if calib_data is not None:
            with autograd.pause():
                n = 0
                if hasattr(calib_data, "reset"):
                    calib_data.reset()
                for batch in calib_data:
                    from ..ndarray import NDArray

                    if isinstance(batch, NDArray):
                        data = batch
                    elif isinstance(batch, (list, tuple)):
                        data = batch[0]
                    else:  # DataBatch
                        data = batch.data[0]
                    network(data)
                    n += 1
                    if num_calib_batches and n >= num_calib_batches:
                        break
    finally:
        for child, orig in hooks:
            child.forward = orig

    for key, (blk, name, child) in targets.items():
        st = stats[key]
        if not onp.isfinite(st["min"]):
            continue  # never saw a batch
        if calib_mode == "entropy" and st["samples"]:
            allv = onp.concatenate(st["samples"])
            hist, edges = onp.histogram(allv, bins=2048)
            t = calib_entropy(hist, edges)
            rng = (-t, t)
        else:
            rng = (st["min"], st["max"])
        wrapper = QuantizedDense(child, rng) if isinstance(child, nn.Dense) \
            else QuantizedConv2D(child, rng)
        shim = _QuantizedShim(wrapper, child)
        blk._children[name] = shim
        # subclassed Blocks call children through instance attributes
        # (self.fc = nn.Dense(...)), not _children — rebind those too
        for attr, val in list(vars(blk).items()):
            if val is child:
                object.__setattr__(blk, attr, shim)
    return network


class _QuantizedShim:
    """Block-API shim standing in for a quantized child. Delegates every
    tree-walk API (params, cast, names) to the wrapped fp32 original so
    save_parameters / collect_params / summary keep working; forward runs
    the int8 wrapper. Built without Block.__init__ so the original is NOT
    re-registered as a child (no double-walk)."""

    def __init__(self, wrapper, original):
        self._wrapper = wrapper
        self._original = original
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []
        self.name = getattr(original, "name", "quantized")
        self.prefix = getattr(original, "prefix", "")

    def __call__(self, x, *args):
        return self._wrapper(x)

    def forward(self, x, *args):
        return self._wrapper(x)

    @property
    def params(self):
        return self._original.params

    def collect_params(self, select=None):
        return self._original.collect_params(select)

    def _collect_params_with_prefix(self, prefix=""):
        return self._original._collect_params_with_prefix(prefix)

    def cast(self, dtype):
        pass  # int8 weights are baked; fp32 originals keep their dtype

    def hybridize(self, active=True, **kwargs):
        pass  # the wrapper body is pure jnp — jit-traceable as-is

    def apply(self, fn):
        fn(self)
        return self

    def initialize(self, *args, **kwargs):
        pass


# ---- symbol-graph quantization pass --------------------------------------
# The reference's main quantization API operates on symbols:
# quantize_model(sym, arg_params, aux_params, ...) rewrites the graph so
# consecutive quantizable ops form int8 regions (quantize_graph_pass.cc),
# with per-tensor calibrated ranges. This is that pass over this package's
# Symbol DAG; quantized ops live in ndarray/ops_quant.py.

def quantize_symbol(sym, excluded_sym_names=(), excluded_op_names=(),
                    calib_ranges=None, quantized_dtype="int8"):
    """Rewrite a Symbol into int8 regions (reference:
    src/operator/quantization/quantize_graph_pass.cc QuantizeGraph;
    python/mxnet/contrib/quantization.py _quantize_symbol).

    Since round 19 this is a thin wrapper over the `analysis/` pass
    pipeline (analysis/quantize.py): a quantize-insertion pass wraps
    each quantizable op in its own int8 island, a dequant→quant elision
    pass merges adjacent islands, and a calibration pass folds the
    range statistics into constant scales — all scheduled by
    ``optimize_symbol`` under the standard post-verify rejection net,
    so a bad int8 rewrite degrades to the fp32 graph instead of wrong
    answers. uint8-producer → int8-consumer boundaries inside merged
    regions are resolved IN-OP (``_to_s8_lattice`` hops uint8 chains
    onto the int8 lattice inside quantized conv/fc), which is what lets
    the elision pass merge islands without caring about payload dtype.

    Returns (qsym, offline_weights) where offline_weights maps each
    conv/fc weight variable name to the (quantized_name, min_name,
    max_name) variables the caller must populate (offline weight
    quantization, reference's `offline_params`).
    """
    from ..analysis import graph_opt
    from ..analysis import quantize as qpass

    auto_dtype = quantized_dtype in ("auto", None)
    if not auto_dtype and quantized_dtype != "int8":
        # global uint8 would zero every negative activation (the uint8
        # lattice here is zero-point-free); only 'auto' may select it,
        # and only for calibrated-non-negative tensors
        raise ValueError("quantized_dtype must be 'int8' or 'auto' "
                         f"(got {quantized_dtype}); 'auto' applies "
                         "uint8 to provably non-negative tensors")
    with qpass.quantize_scope(
            excluded_sym_names=excluded_sym_names,
            excluded_op_names=excluded_op_names,
            calib_ranges=calib_ranges or {},
            auto_dtype=auto_dtype) as scope:
        qsym, stats = graph_opt.optimize_symbol(
            sym, level=1, subject="quantize",
            passes=qpass.QUANTIZE_PIPELINE)
        if scope.islands == 0 or stats.get("rejected"):
            # nothing quantizable (or the post-verify net threw the
            # rewrite out): serve the fp32 graph unchanged
            return sym, {}
        return qsym, dict(scope.offline)


def _collect_layer_statistics(sym, feed, calib_data, data_names,
                              calib_mode, num_calib_batches=None,
                              logger=None):
    """Run the fp32 graph over calibration batches collecting per-tensor
    ranges (reference: quantization.py _collect_layer_statistics /
    _LayerOutputMinMaxCollector). Returns {tensor_name: (min, max)}."""
    import numpy as _onp

    from ..ndarray import NDArray

    internals = sym.get_internals()
    nodes = [s for s in internals._group if s._op is not None]
    stats = {}
    samples = {}
    _CAP = 8192
    rng = _onp.random.RandomState(0)
    n = 0
    for batch in calib_data:
        if isinstance(batch, NDArray):
            datas = [batch]
        elif isinstance(batch, (list, tuple)):
            datas = list(batch)
        else:
            datas = list(batch.data)
        f = dict(feed)
        for dn_, d in zip(data_names, datas):
            f[dn_] = d
        cache = {}
        for s in nodes:
            out = s._eval_nodes(f, cache)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for nm, o in zip(s.list_outputs(), outs):
                v = _onp.asarray(o.asnumpy(), dtype=_onp.float32).ravel()
                mnmx = stats.get(nm)
                cur = (float(v.min()), float(v.max()))
                stats[nm] = cur if mnmx is None else (
                    min(mnmx[0], cur[0]), max(mnmx[1], cur[1]))
                if calib_mode == "entropy":
                    av = _onp.abs(v)
                    if av.size > _CAP:
                        av = av[rng.choice(av.size, _CAP, replace=False)]
                    samples.setdefault(nm, []).append(av)
        n += 1
        if num_calib_batches and n >= num_calib_batches:
            break
    if calib_mode == "entropy":
        for nm, chunks in samples.items():
            allv = _onp.concatenate(chunks)
            if allv.size == 0 or float(allv.max()) == 0.0:
                continue
            hist, edges = _onp.histogram(allv, bins=2048)
            t = calib_entropy(hist, edges)
            stats[nm] = (-t, t)
    if logger:
        logger.info("collected ranges for %d tensors over %d batches",
                    len(stats), n)
    return stats


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), excluded_op_names=(),
                   calib_mode="naive", calib_data=None,
                   num_calib_batches=None, quantized_dtype="int8",
                   logger=None):
    """Post-training quantization of a symbolic model (reference:
    python/mxnet/contrib/quantization.py quantize_model). Returns
    (qsym, qarg_params, aux_params).

    calib_mode: 'none' (ranges computed on the fly per batch), 'naive'
    (min/max over calib_data), 'entropy' (KL threshold per tensor).
    """
    import numpy as _onp

    calib_ranges = {}
    if calib_mode != "none":
        if calib_data is None:
            raise ValueError(f"calib_mode='{calib_mode}' needs calib_data")
        feed = {}
        for k, v in list(arg_params.items()) + list(aux_params.items()):
            feed[k] = v
        calib_ranges = _collect_layer_statistics(
            sym, feed, calib_data, data_names, calib_mode,
            num_calib_batches, logger)
    qsym, offline = quantize_symbol(
        sym, excluded_sym_names=excluded_sym_names,
        excluded_op_names=excluded_op_names, calib_ranges=calib_ranges,
        quantized_dtype=quantized_dtype)
    from .. import nd

    from ..analysis import quantize as qpass

    qarg = dict(arg_params)
    for wname, (qn, mnn, mxn) in offline.items():
        w = arg_params[wname]
        wv = w.asnumpy()
        amax = float(_onp.abs(wv).max()) or 1e-20
        scale = 127.0 / amax
        qarg[qn] = nd.array(
            _onp.clip(_onp.rint(wv * scale), -127, 127).astype("int8"),
            dtype="int8")
        qarg[mnn] = nd.array([-amax])
        qarg[mxn] = nd.array([amax])
        # fp32 -> int8 storage: 3 of every 4 weight bytes stop moving
        qpass._count("weight_bytes_saved", 3 * int(wv.size))
    # drop fp32 weights ONLY if no surviving node references them
    # (tied weights / partially-excluded sharing keep the fp32 binding)
    still_needed = set(qsym.list_arguments())
    for wname in offline:
        if wname not in still_needed:
            del qarg[wname]
    return qsym, qarg, dict(aux_params)


def quantize_net_graph(network, calib_data=None, calib_mode="naive",
                       quantized_dtype="int8", exclude_layers=(),
                       exclude_layers_match=(), exclude_operators=(),
                       num_calib_batches=None, input_names=("data",),
                       logger=None):
    """Graph-mode gluon quantization (the reference architecture:
    python/mxnet/contrib/quantization.py quantize_net traces the
    HybridBlock to a symbol, runs the quantize_model graph pass, and
    returns a SymbolBlock). Unlike the block-swap ``quantize_net``,
    consecutive quantizable layers here form single int8 regions —
    conv→bn→relu→pool chains never round-trip through fp32.

    ``exclude_layers`` matches symbol node names (the traced op names,
    e.g. 'hybridsequential0_conv0'); ``exclude_operators`` matches op
    types ('pooling', 'batch_norm', ...).
    """
    from .. import symbol as S
    from ..gluon.block import SymbolBlock

    # deferred-init params need one eager forward to learn their shapes
    # before the symbolic trace (reference quantize_net runs the block on
    # dummy data for the same reason)
    from ..gluon.parameter import DeferredInitializationError

    try:
        needs_shape = any(p._ndarray is None
                          for p in network.collect_params().values())
    except Exception:
        needs_shape = True
    if needs_shape:
        if calib_data is None:
            raise ValueError(
                "network has uninitialized (deferred) parameters; pass "
                "calib_data so a shape-materializing forward can run")
        from .. import autograd
        from ..ndarray import NDArray

        first = calib_data[0] if isinstance(calib_data, (list, tuple)) \
            else next(iter(calib_data))
        datas = [first] if isinstance(first, NDArray) else (
            list(first) if isinstance(first, (list, tuple))
            else list(first.data))
        with autograd.pause(train_mode=False):
            network(*datas[:len(input_names)])
        if hasattr(calib_data, "reset"):
            calib_data.reset()

    out = network(*[S.var(n) for n in input_names])
    if isinstance(out, (list, tuple)):
        out = S.Group(list(out))  # multi-output block: group the heads
    exclude_layers = set(exclude_layers)
    if exclude_layers_match:
        # reference quantize_net exclude_layers_match: substring match
        # against traced node names
        for s in out._walk():
            nm = s._name or ""
            if s._op is not None and any(pat in nm
                                         for pat in exclude_layers_match):
                exclude_layers.add(nm)
    aux_names = set()
    for s in out._walk():
        if s._op == "batch_norm" and len(s._inputs) >= 5:
            aux_names.update(i._name for i in s._inputs[3:5]
                             if i._op is None)
    arg_params, aux_params = {}, {}
    for name, p in network.collect_params().items():
        (aux_params if name in aux_names else arg_params)[name] = p.data()

    qsym, qarg, qaux = quantize_model(
        out, arg_params, aux_params, data_names=tuple(input_names),
        excluded_sym_names=tuple(exclude_layers),
        excluded_op_names=tuple(exclude_operators),
        calib_mode=calib_mode, calib_data=calib_data,
        num_calib_batches=num_calib_batches,
        quantized_dtype=quantized_dtype, logger=logger)

    inputs = [S.var(n) for n in input_names]
    block = SymbolBlock(qsym, inputs)
    params = block.collect_params()
    for name, val in {**qarg, **qaux}.items():
        if name in params:
            p = params[name]
            # dtype must be set BEFORE init so the deferred-init path
            # materializes int8 weights as int8
            p.dtype = val.dtype
            p._load_init_from(val)
    return block
