"""Legacy experimental autograd API (reference:
python/mxnet/contrib/autograd.py — the pre-mx.autograd surface old
scripts import). Thin adapters over mxnet_tpu.autograd."""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..ndarray import NDArray

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Reference contrib/autograd.py:32 — returns the previous state."""
    prev = _ag.is_recording()
    _ag.set_recording(bool(is_train))
    _ag.set_training(bool(is_train))
    return prev


class TrainingStateScope:
    """Reference contrib/autograd.py:54."""

    def __init__(self, enter_state):
        self._enter_state = bool(enter_state)
        self._prev_r = None
        self._prev_t = None

    def __enter__(self):
        self._prev_r = _ag.set_recording(self._enter_state)
        self._prev_t = _ag.set_training(self._enter_state)

    def __exit__(self, *exc):
        _ag.set_recording(self._prev_r)
        _ag.set_training(self._prev_t)


def train_section():
    """``with autograd.train_section():`` legacy recording scope."""
    return TrainingStateScope(True)


def test_section():
    return TrainingStateScope(False)


def mark_variables(variables, gradients, grad_reqs="write"):
    _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    _ag.backward(outputs, out_grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """Reference contrib/autograd.py:158 — backward with ones heads."""
    _ag.backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator: f(*args) -> (grads, outputs) (reference :163)."""

    @functools.wraps(func)
    def wrapped(*args):
        from .. import ndarray as nd

        variables = list(args)
        if argnum is not None:
            nums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in nums]
        for v in variables:
            if not isinstance(v, NDArray):
                raise TypeError("arguments must be NDArrays")
        # fresh zero buffers EVERY call (reference does the same):
        # reused buffers would leak grad_req='add' accumulation or
        # stale values for variables unused by func
        _ag.mark_variables(
            variables,
            [nd.zeros(v.shape, dtype=str(v.dtype)) for v in variables])
        with TrainingStateScope(True):
            outputs = func(*args)
        _ag.backward([outputs] if isinstance(outputs, NDArray)
                     else list(outputs))
        grads = [v.grad for v in variables]
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """Decorator returning only the gradients (reference :195)."""
    g_and_l = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return g_and_l(*args)[0]

    return wrapped
