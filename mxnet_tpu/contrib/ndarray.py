"""Namespace shim (reference: python/mxnet/contrib/ndarray.py is an
autogen re-export of the contrib op surface). ``mx.contrib.ndarray.*``
== ``mx.nd.contrib.*``."""
from ..ndarray.contrib import *  # noqa: F401,F403
