"""SVRG optimization (reference:
python/mxnet/contrib/svrg_optimization/__init__.py)."""
from .svrg_module import SVRGModule  # noqa: F401
from .svrg_optimizer import _SVRGOptimizer  # noqa: F401
