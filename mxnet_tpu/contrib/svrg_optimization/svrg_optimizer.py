"""SVRG gradient-corrected optimizer.

Reference: python/mxnet/contrib/svrg_optimization/svrg_optimizer.py —
a wrapper optimizer that (a) assigns full-gradient snapshots into the
kvstore for the special keys and (b) applies the variance-reduced update
g_corrected = g - g_snapshot(w) + mean_full_grad for normal keys.
"""
from __future__ import annotations

from ... import optimizer as _opt

__all__ = ["_SVRGOptimizer"]


@_opt.register
class _AssignmentOptimizer(_opt.Optimizer):
    """kvstore 'update' that just overwrites the stored value (used for
    the full-gradient bookkeeping keys; reference svrg_optimizer.py:30)."""

    def update(self, index, weight, grad, state):
        weight._data = grad.data


@_opt.register
class _SVRGOptimizer(_opt.Optimizer):
    """Dispatch: special-key gradients are assigned, normal keys run the
    wrapped default optimizer (reference svrg_optimizer.py:60)."""

    def __init__(self, default_optimizer, **kwargs):
        # pull out the wrapped optimizer's kwargs
        super().__init__(rescale_grad=kwargs.pop("rescale_grad", 1.0),
                         learning_rate=kwargs.pop("learning_rate", 0.01),
                         wd=kwargs.pop("wd", 0.0))
        if isinstance(default_optimizer, str):
            self.default_opt = _opt.create(
                default_optimizer, learning_rate=self.lr, wd=self.wd,
                rescale_grad=self.rescale_grad, **kwargs)
        else:
            self.default_opt = default_optimizer
        self.aux_opt = _AssignmentOptimizer()

    def update(self, index, weight, grad, state):
        if self._is_special_key(index):
            self.aux_opt.update(index, weight, grad, None)
        else:
            self.default_opt.update(index, weight, grad, state)

    def create_state(self, index, weight):
        if self._is_special_key(index):
            return None
        return self.default_opt.create_state(index, weight)

    @staticmethod
    def _is_special_key(index):
        name = str(index)
        return name.startswith("key_") or name.endswith("_full")
