"""SVRGModule: Module with stochastic variance-reduced gradients.

Reference: python/mxnet/contrib/svrg_optimization/svrg_module.py.
Maintains a snapshot of the parameters taken every ``update_freq``
epochs plus the full-dataset gradient at that snapshot; each minibatch
update uses g(w) - g(w_snapshot) + mean_full_grad ('Accelerating
Stochastic Gradient Descent using Predictive Variance Reduction',
Johnson & Zhang 2013).
"""
from __future__ import annotations

import logging

from ... import ndarray as nd
from ...module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        self._param_dict = None  # full grads at the snapshot
        self._snapshot_taken = False

    # -- plumbing that must mirror into the snapshot module ------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module,
                     grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None, grad_req)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  force_init=True, allow_missing=False)

    def take_snapshot(self):
        """Copy current params into the snapshot module (reference:
        svrg_module.py update_full_grads prologue)."""
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg, aux)
        self._snapshot_taken = True

    def update_full_grads(self, train_data):
        """One full pass over train_data at the snapshot params to
        compute the mean full gradient (reference svrg_module.py:207)."""
        self.take_snapshot()
        mod = self._mod_aux
        accum = {}
        nbatch = 0
        if hasattr(train_data, "reset"):
            train_data.reset()
        for batch in train_data:
            mod.forward(batch, is_train=True)
            mod.backward()
            nbatch += 1
            for name in mod._param_names():
                g = mod._exec.grad_dict.get(name)
                if g is None:
                    continue
                if name in accum:
                    accum[name] = accum[name] + g
                else:
                    accum[name] = g.copy()
        self._param_dict = {k: v / max(nbatch, 1)
                            for k, v in accum.items()}
        if hasattr(train_data, "reset"):
            train_data.reset()

    def _update_svrg_gradients(self, data_batch):
        """Replace this module's gradients with the variance-reduced
        combination (reference svrg_module.py:233)."""
        mod = self._mod_aux
        mod.forward(data_batch, is_train=True)
        mod.backward()
        for name in self._param_names():
            g = self._exec.grad_dict.get(name)
            g_snap = mod._exec.grad_dict.get(name)
            if g is None or g_snap is None or \
                    self._param_dict is None or \
                    name not in self._param_dict:
                continue
            g._data = (g - g_snap + self._param_dict[name]).data

    def forward_backward(self, data_batch):
        super().forward(data_batch, is_train=True)
        super().backward()
        if self._snapshot_taken and self._param_dict is not None:
            self._update_svrg_gradients(data_batch)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd", optimizer_params=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """Training loop with a full-gradient refresh every
        ``update_freq`` epochs (reference svrg_module.py fit)."""
        from ... import metric as _metric

        assert num_epoch is not None
        self.bind([(d.name, d.shape) if hasattr(d, "name") else d
                   for d in train_data.provide_data],
                  [(d.name, d.shape) if hasattr(d, "name") else d
                   for d in train_data.provide_label],
                  for_training=True, force_rebind=force_rebind)
        from ... import initializer as _init

        self.init_params(initializer or _init.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params or
                            (("learning_rate", 0.01),))
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        for epoch in range(begin_epoch, num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            if hasattr(train_data, "reset"):
                train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    for cb in (batch_end_callback if isinstance(
                            batch_end_callback, (list, tuple))
                            else [batch_end_callback]):
                        cb(type("P", (), {"epoch": epoch,
                                          "nbatch": nbatch,
                                          "eval_metric": eval_metric})())
            self.logger.info("Epoch[%d] Train-%s=%f", epoch,
                             *eval_metric.get())
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                for cb in (epoch_end_callback if isinstance(
                        epoch_end_callback, (list, tuple))
                        else [epoch_end_callback]):
                    cb(epoch, self._symbol, arg, aux)
