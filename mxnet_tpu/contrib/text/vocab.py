"""Indexed vocabulary (reference: python/mxnet/contrib/text/vocab.py)."""
from __future__ import annotations

import collections

__all__ = ["Vocabulary"]


class Vocabulary:
    """Token <-> index mapping built from a Counter.

    Reference: vocab.py:Vocabulary — same ordering rules (frequency
    desc, then alphabetical), reserved tokens first, index 0 = unknown.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            res = set(reserved_tokens)
            if len(res) != len(reserved_tokens):
                raise ValueError("reserved tokens must be unique")
            if unknown_token in res:
                raise ValueError("unknown token cannot be reserved")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) if reserved_tokens \
            else None
        self._idx_to_token = [unknown_token] + (
            list(reserved_tokens) if reserved_tokens else [])
        # plain dict: a defaultdict would INSERT unknown tokens on
        # lookup, corrupting later membership checks
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        existing = set(self._idx_to_token)
        pairs = sorted(counter.items(), key=lambda kv: kv[0])
        pairs.sort(key=lambda kv: kv[1], reverse=True)
        kept = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and kept >= most_freq_count:
                break
            if token not in existing:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                kept += 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices (unknown -> 0)."""
        if isinstance(tokens, str):
            return self._token_to_idx.get(tokens, 0)
        return [self._token_to_idx.get(t, 0) for t in tokens]

    def to_tokens(self, indices):
        if isinstance(indices, int):
            indices = [indices]
            single = True
        else:
            single = False
        out = []
        for i in indices:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"index {i} out of vocabulary range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out
