"""Token embeddings (reference: python/mxnet/contrib/text/embedding.py).

Pretrained GloVe/fastText downloads need egress; the file-backed
CustomEmbedding path (the same loader those use underneath) is fully
functional, and `register`/`create` keep the registry API.
"""
from __future__ import annotations

import io
import logging
import os

import numpy as onp

from ... import ndarray as nd
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding"]

_REGISTRY = {}


def register(cls):
    """Reference: embedding.py:register."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    """Reference: embedding.py:create."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown embedding '{embedding_name}'; registered: "
            f"{sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Reference: embedding.py:get_pretrained_file_names."""
    out = {name: list(getattr(cls, "pretrained_file_names", []))
           for name, cls in _REGISTRY.items()}
    if embedding_name is not None:
        return out[embedding_name.lower()]
    return out


class TokenEmbedding(Vocabulary):
    """Base embedding: vocabulary + vector table (reference
    embedding.py:_TokenEmbedding)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_txt(self, path, elem_delim=" ",
                            encoding="utf8"):
        """Parse a '<token> <v0> <v1> ...' file (the GloVe/fastText text
        format; reference _load_embedding)."""
        tokens, vecs = [], []
        with io.open(path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2:
                    continue  # fastText header "count dim"
                token, elems = parts[0], parts[1:]
                if not elems:
                    logging.warning("skipping token %r with no vector",
                                    token)
                    continue
                if self._vec_len and len(elems) != self._vec_len:
                    logging.warning("skipping token %r with bad length",
                                    token)
                    continue
                self._vec_len = self._vec_len or len(elems)
                tokens.append(token)
                vecs.append([float(x) for x in elems])
        table = onp.zeros((len(self._idx_to_token) + len(tokens),
                           self._vec_len), "float32")
        for token, vec in zip(tokens, vecs):
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
            table[self._token_to_idx[token]] = vec
        self._idx_to_vec = nd.array(
            table[:len(self._idx_to_token)])

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Reference: embedding.py:get_vecs_by_tokens."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idxs = []
        for t in toks:
            if t in self._token_to_idx:
                idxs.append(self._token_to_idx[t])
            elif lower_case_backup and t.lower() in self._token_to_idx:
                idxs.append(self._token_to_idx[t.lower()])
            else:
                idxs.append(0)
        vecs = self._idx_to_vec.asnumpy()[idxs]
        out = nd.array(vecs)
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        """Reference: embedding.py:update_token_vectors."""
        if isinstance(tokens, str):
            tokens = [tokens]
        table = onp.array(self._idx_to_vec.asnumpy())  # writable copy
        newv = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else onp.asarray(new_vectors)
        newv = newv.reshape(len(tokens), -1)
        for t, v in zip(tokens, newv):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is unknown; only tokens "
                                 "in the vocabulary can be updated")
            table[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(table)


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding from a user text file (reference
    embedding.py:CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", vocabulary=None, **kwargs):
        if vocabulary is not None:
            super().__init__(counter=None, **kwargs)
            # seed vocab from the provided vocabulary's tokens
            for t in vocabulary.idx_to_token[1:]:
                if t not in self._token_to_idx:
                    self._token_to_idx[t] = len(self._idx_to_token)
                    self._idx_to_token.append(t)
        else:
            super().__init__(counter=None, **kwargs)
        if not os.path.exists(pretrained_file_path):
            raise FileNotFoundError(pretrained_file_path)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 encoding)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference
    embedding.py:CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        super().__init__()
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = vocabulary.token_to_idx
        parts = [e.get_vecs_by_tokens(self._idx_to_token).asnumpy()
                 for e in token_embeddings]
        table = onp.concatenate(parts, axis=1)
        self._vec_len = table.shape[1]
        self._idx_to_vec = nd.array(table)


__all__.append("CompositeEmbedding")
