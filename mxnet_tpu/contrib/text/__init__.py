"""Text utilities (reference: python/mxnet/contrib/text/__init__.py)."""
from . import utils  # noqa: F401
from . import vocab  # noqa: F401
from . import embedding  # noqa: F401
from .vocab import Vocabulary  # noqa: F401
