"""mx.contrib (reference: python/mxnet/contrib/__init__.py)."""
from . import amp  # noqa: F401
from . import autograd  # noqa: F401  (legacy experimental API)
from . import io  # noqa: F401
from . import ndarray  # noqa: F401  (namespace shim)
from . import symbol  # noqa: F401  (namespace shim)
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import text  # noqa: F401
from . import tensorboard  # noqa: F401
