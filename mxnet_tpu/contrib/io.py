"""Legacy contrib IO (reference: python/mxnet/contrib/io.py —
DataLoaderIter adapts a gluon DataLoader to the DataIter interface)."""
from __future__ import annotations

from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Wrap a ``gluon.data.DataLoader`` as a classic DataIter
    (reference contrib/io.py:25 — provide_data/provide_label are
    inferred from the first batch so Module.fit can bind)."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        self._data_name = data_name
        self._label_name = label_name
        # peek the first batch for shapes (reference does the same);
        # it is replayed as the first next() result
        self._peek = self._fetch()
        first = self._peek
        self.provide_data = [DataDesc(data_name, first.data[0].shape,
                                      str(first.data[0].dtype))]
        self.provide_label = [DataDesc(label_name, l.shape, str(l.dtype))
                              for l in first.label[:1]]
        self.batch_size = first.data[0].shape[0]

    def _fetch(self):
        batch = next(self._iter)  # StopIteration propagates
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return DataBatch(data=[batch[0]], label=[batch[1]], pad=0)
        return DataBatch(data=[batch], label=[], pad=0)

    def reset(self):
        self._iter = iter(self._loader)
        self._peek = None

    def next(self):
        if self._peek is not None:
            b, self._peek = self._peek, None
            return b
        return self._fetch()

    __next__ = next
