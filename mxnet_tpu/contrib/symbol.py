"""Namespace shim (reference: python/mxnet/contrib/symbol.py).
``mx.contrib.symbol.*`` == ``mx.sym.contrib.*``."""
from ..symbol.contrib import *  # noqa: F401,F403
