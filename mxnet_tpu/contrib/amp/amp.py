"""Automatic mixed precision (reference: python/mxnet/contrib/amp/amp.py).

TPU-native policy: target dtype defaults to **bfloat16** — the MXU's
native input type; fp32 accumulation comes free from XLA, so unlike the
reference's fp16 flow no loss scaling is required by default (the dynamic
LossScaler remains available and is exercised for fp16 parity). `init()`
activates op-list-driven input casting inside the op dispatch layer
(reference wraps every registered op at init, amp.py:251; here the
registry applies the cast inside each op's pure function so the casts live
on the tape/jaxpr and XLA fuses them into the MXU ops).
"""
from __future__ import annotations

from contextlib import contextmanager

from . import lists
from .loss_scaler import LossScaler
from ...ndarray import registry as _registry

_state = {"initialized": False, "target_dtype": None}


def init(target_dtype="bfloat16"):
    """Turn on AMP for all subsequently executed ops."""
    assert target_dtype in ("bfloat16", "float16"), target_dtype
    _registry.set_amp(target_dtype,
                      target_ops=lists.TARGET_DTYPE_OPS,
                      fp32_ops=lists.FP32_OPS,
                      widest_ops=lists.WIDEST_TYPE_CASTS)
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype


def disable():
    """Turn AMP back off (testing convenience; reference has no inverse)."""
    _registry.set_amp(None)
    _state["initialized"] = False
    _state["target_dtype"] = None


def init_trainer(trainer):
    """Attach a dynamic loss scaler to a Gluon Trainer (reference:
    amp.py:288 init_trainer)."""
    if not _state["initialized"]:
        raise RuntimeError("call amp.init() before amp.init_trainer()")
    trainer._amp_loss_scaler = LossScaler()
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """`with amp.scale_loss(loss, trainer) as scaled: scaled.backward()`
    (reference: amp.py scale_loss). Scales the loss up; trainer.step
    divides gradients back down and skips the step on overflow."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def convert_model(net, target_dtype="bfloat16"):
    """Cast a Gluon block's parameters/compute to the target dtype, keeping
    norm layers fp32 (reference: amp.py convert_model / the
    low_precision_pass.cc graph rewrite; BatchNorm.cast pins its params
    fp32 here)."""
    net.cast(target_dtype)
    return net


convert_hybrid_block = convert_model
