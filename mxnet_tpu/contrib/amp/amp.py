"""Automatic mixed precision (reference: python/mxnet/contrib/amp/amp.py).

TPU-native policy: target dtype defaults to **bfloat16** — the MXU's
native input type; fp32 accumulation comes free from XLA, so unlike the
reference's fp16 flow no loss scaling is required by default (the dynamic
LossScaler remains available and is exercised for fp16 parity). `init()`
activates op-list-driven input casting inside the op dispatch layer
(reference wraps every registered op at init, amp.py:251; here the
registry applies the cast inside each op's pure function so the casts live
on the tape/jaxpr and XLA fuses them into the MXU ops).
"""
from __future__ import annotations

from contextlib import contextmanager

from . import lists
from .loss_scaler import LossScaler
from ...ndarray import registry as _registry

_state = {"initialized": False, "target_dtype": None}
_NODE_SERIAL = [0]  # process-wide uniquifier for inserted graph nodes


def init(target_dtype="bfloat16"):
    """Turn on AMP for all subsequently executed ops."""
    assert target_dtype in ("bfloat16", "float16"), target_dtype
    _registry.set_amp(target_dtype,
                      target_ops=lists.TARGET_DTYPE_OPS,
                      fp32_ops=lists.FP32_OPS,
                      widest_ops=lists.WIDEST_TYPE_CASTS,
                      conditional_ops=lists.CONDITIONAL_FP32_OPS)
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype


def disable():
    """Turn AMP back off (testing convenience; reference has no inverse)."""
    _registry.set_amp(None)
    _state["initialized"] = False
    _state["target_dtype"] = None


def init_trainer(trainer):
    """Attach a dynamic loss scaler to a Gluon Trainer (reference:
    amp.py:288 init_trainer)."""
    if not _state["initialized"]:
        raise RuntimeError("call amp.init() before amp.init_trainer()")
    trainer._amp_loss_scaler = LossScaler()
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """`with amp.scale_loss(loss, trainer) as scaled: scaled.backward()`
    (reference: amp.py scale_loss). Scales the loss up; trainer.step
    divides gradients back down and skips the step on overflow."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def convert_symbol(sym, target_dtype="bfloat16", target_dtype_ops=None,
                   fp32_ops=None, widest_dtype_ops=None,
                   excluded_sym_names=()):
    """Graph-conversion pass: rebuild the Symbol DAG with amp_cast /
    amp_multicast nodes at op boundaries per the op lists.

    Reference: amp.py convert_symbol → src/nnvm/low_precision_pass.cc
    ReducePrecision. Target-list ops get their inputs amp_cast to the
    target dtype, fp32-list ops get amp_cast to float32 (amp_cast only
    touches floating tensors, so casting blindly is safe), widest-list
    ops route all inputs through one amp_multicast node. The pass is
    purely structural — no parameter values are touched — so the result
    works under bind/simple_bind, tojson, and ONNX export alike.
    """
    from ...symbol import Symbol

    tgt = set(lists.TARGET_DTYPE_OPS if target_dtype_ops is None
              else target_dtype_ops)
    f32 = set(lists.FP32_OPS if fp32_ops is None else fp32_ops)
    widest = set(lists.WIDEST_TYPE_CASTS if widest_dtype_ops is None
                 else widest_dtype_ops)
    excluded = set(excluded_sym_names)
    memo = {}
    # tojson collapses nodes BY NAME — every inserted node needs a name
    # unique across ALL conversions (re-converting an already-converted
    # graph must not mint a second node with a first-pass name)
    serial = _NODE_SERIAL

    def cast_in(s, dtype, tag):
        serial[0] += 1
        nm = (f"{s._name or s._op or 'sym'}_amp_cast_{dtype}_"
              f"{tag}_{serial[0]}")
        return Symbol(op="amp_cast", name=nm, inputs=[s],
                      kwargs={"dtype": dtype})

    def conv(s):
        # output views of one multi-output node share the base node's
        # _inputs/_kwargs objects — memoize by THAT identity so every
        # view maps onto views of ONE converted node (names stay unique
        # for tojson, and the eval cache's shared-identity keying holds)
        if s._group is not None:
            key = id(s)
        elif s._op is None:
            key = id(s)
        else:
            key = (s._op, id(s._inputs), id(s._kwargs), s._name)
        base = memo.get(key)
        if base is None:
            if s._group is not None:
                base = Symbol(group=[conv(g) for g in s._group])
                memo[key] = base
                return base
            ins = [conv(i) for i in s._inputs]
            op, name = s._op, s._name
            cond_f32 = any(
                op == c_op and str(s._kwargs.get(c_attr)) in c_vals
                for c_op, c_attr, c_vals in lists.CONDITIONAL_FP32_OPS)
            if op is not None and name not in excluded:
                if cond_f32:
                    ins = [cast_in(x, "float32", i)
                           for i, x in enumerate(ins)]
                elif op in tgt:
                    ins = [cast_in(x, target_dtype, i)
                           for i, x in enumerate(ins)]
                elif op in f32:
                    ins = [cast_in(x, "float32", i)
                           for i, x in enumerate(ins)]
                elif op in widest and len(ins) > 1:
                    serial[0] += 1
                    mc = Symbol(op="amp_multicast",
                                name=f"{name or op}_amp_multicast_"
                                     f"{serial[0]}",
                                inputs=ins,
                                kwargs={"num_outputs": len(ins)},
                                num_outputs=len(ins))
                    ins = [mc[i] for i in range(len(ins))]
            base = Symbol(op=op, name=name, inputs=ins,
                          kwargs=dict(s._kwargs),
                          num_outputs=s._num_outputs)
            base._attrs = dict(s._attrs)  # graft-lint: allow(L601)
            memo[key] = base
        if s._op is not None and s._num_outputs > 1:
            return base[s._output_index]
        return base

    return conv(sym)


def convert_model(sym_or_net, arg_params=None, aux_params=None,
                  target_dtype="bfloat16", **kwargs):
    """Reference amp.py convert_model: symbolic (sym, arg_params,
    aux_params) -> converted triple via the graph pass. Passing a Gluon
    block keeps the round-1 behavior (cast with norm layers pinned
    fp32), including the old positional form convert_model(net, dtype)."""
    from ...symbol import Symbol

    if isinstance(sym_or_net, Symbol):
        out = convert_symbol(sym_or_net, target_dtype=target_dtype,
                             **kwargs)
        return out, dict(arg_params or {}), dict(aux_params or {})
    if isinstance(arg_params, str):  # legacy convert_model(net, "float16")
        target_dtype = arg_params
    elif arg_params is not None or aux_params is not None:
        raise TypeError(
            "arg_params/aux_params only apply to symbolic conversion; "
            "for Gluon blocks use convert_model(net, target_dtype=...)")
    sym_or_net.cast(target_dtype)
    return sym_or_net


def convert_hybrid_block(net, target_dtype="bfloat16"):
    """Cast a Gluon block's parameters/compute to the target dtype, keeping
    norm layers fp32 (reference: amp.py convert_hybrid_block; BatchNorm.cast
    pins its params fp32 here)."""
    net.cast(target_dtype)
    return net
