"""AMP (reference: python/mxnet/contrib/amp/__init__.py)."""
from .amp import (init, disable, init_trainer, scale_loss, convert_model,
                  convert_hybrid_block, convert_symbol)
from .loss_scaler import LossScaler
from . import lists

__all__ = ["init", "disable", "init_trainer", "scale_loss", "convert_model",
           "convert_hybrid_block", "convert_symbol", "LossScaler", "lists"]
