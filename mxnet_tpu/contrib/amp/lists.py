"""AMP op lists (reference: python/mxnet/contrib/amp/lists/symbol.py:22-511).

Classification of registered ops for automatic mixed precision:
- TARGET_DTYPE_OPS: run in the low-precision target (bf16 on TPU — these
  are the MXU ops where bf16 doubles throughput)
- FP32_OPS: numerically sensitive, always fp32
- WIDEST_TYPE_CASTS: multi-input ops computed in the widest operand type
- CONDITIONAL_FP32_OPS: fp32 only when a named attr takes listed values
  (reference symbol.py:504 CONDITIONAL_FP32_FUNCS — softrelu's exp and
  elu/selu's expm1 overflow in 16-bit)
Everything unlisted runs in whatever dtype its inputs already have.
"""

TARGET_DTYPE_OPS = [
    "convolution", "deconvolution", "fully_connected", "dot", "batch_dot",
    "rnn", "_matmul",
]

FP32_OPS = [
    "batch_norm", "layer_norm", "instance_norm", "group_norm", "l2_normalization",
    "lrn", "softmax", "log_softmax", "softmin", "softmax_cross_entropy",
    "softmax_output", "exp", "expm1", "log", "log10", "log1p", "log2",
    "linear_regression_output", "mae_regression_output",
    "logistic_regression_output", "svm_output", "make_loss", "ctc_loss",
    "erf", "erfinv", "gamma", "gammaln", "norm", "mean", "mean_all", "sum",
    "sum_axis", "nansum", "prod", "nanprod", "rsqrt", "rcbrt", "square",
    "reciprocal", "smooth_l1", "power", "broadcast_power",
]

WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_hypot",
    "broadcast_mod", "elemwise_add", "elemwise_sub", "elemwise_mul",
    "elemwise_div", "add_n", "concat", "stack", "where", "maximum",
    "minimum", "batch_take", "take_along_axis",
]


CONDITIONAL_FP32_OPS = [
    ("activation", "act_type", ["softrelu"]),
    ("leaky_relu", "act_type", ["elu", "selu"]),
]
