"""Dynamic loss scaler (reference: python/mxnet/contrib/amp/loss_scaler.py).

Needed for fp16 parity; bf16 on TPU has fp32's exponent range so the
default bf16 policy trains without scaling (the scaler still works if
enabled)."""
from __future__ import annotations


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference: multi_all_finite
        kernel, src/operator/contrib/all_finite.cc)."""
        from ... import nd

        grads = [p.grad() for p in params if p.grad_req != "null"]
        if not grads:
            return False
        ok = nd.all_finite(*grads)
        return not bool(ok.asnumpy().item())

    def update_scale(self, overflow):
        """Halve on overflow; double every scale_window clean steps."""
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
