"""Dynamic loss scaler (reference: python/mxnet/contrib/amp/loss_scaler.py).

Needed for fp16 parity; bf16 on TPU has fp32's exponent range so the
default bf16 policy trains without scaling (the scaler still works if
enabled).

Under the Trainer's compiled fused step (gluon/fused_step.py) the scale,
grow-window counter and skip count live ON DEVICE inside the donated
step executable — the overflow check and grow/backoff never round-trip
to the host. The host fields here then lag the device; reading
``loss_scale`` syncs them back (one scalar device read), so
``amp.scale_loss`` always multiplies by the same scale the executable
will divide by."""
from __future__ import annotations


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self._loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0
        self._device_sync = None  # set by Trainer when state moves on device

    @property
    def loss_scale(self):
        if self._device_sync is not None:
            self._device_sync()
        return self._loss_scale

    @loss_scale.setter
    def loss_scale(self, value):
        # an external write re-seeds the device state on the next fused
        # step (the Trainer compares against its seed-time mirror)
        self._loss_scale = float(value)

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference: multi_all_finite
        kernel, src/operator/contrib/all_finite.cc)."""
        from ... import nd

        grads = [p.grad() for p in params if p.grad_req != "null"]
        if not grads:
            return False
        ok = nd.all_finite(*grads)
        return not bool(ok.asnumpy().item())

    def update_scale(self, overflow):
        """Halve on overflow; double every scale_window clean steps."""
        if overflow:
            self._loss_scale = max(1.0,
                                   self._loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self._loss_scale *= self._scale_factor
                self._unskipped = 0
