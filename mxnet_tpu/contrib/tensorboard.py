"""TensorBoard logging (reference: python/mxnet/contrib/tensorboard.py).

The reference delegates to the external `tensorboard` package; this
build writes genuine TensorBoard event files itself — tfrecord framing
(masked crc32c) around hand-encoded Event/Summary protobuf messages —
so `tensorboard --logdir` reads them with no extra dependency.
"""
from __future__ import annotations

import os
import struct
import time

__all__ = ["SummaryWriter", "LogMetricsCallback"]

# ---------------------------------------------------------------- crc32c

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ (poly if c & 1 else 0)
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------- minimal protobuf encoding

def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _field(num, wire, payload):
    return _varint(num << 3 | wire) + payload


def _encode_summary_value(tag, value):
    # Summary.Value { string tag = 1; float simple_value = 2; }
    tag_b = tag.encode()
    body = _field(1, 2, _varint(len(tag_b)) + tag_b)
    body += _field(2, 5, struct.pack("<f", float(value)))
    return body


def _encode_event(step, tag_values, wall_time=None):
    # Event { double wall_time = 1; int64 step = 2; Summary summary = 5; }
    # Summary { repeated Value value = 1; }
    summary = b""
    for tag, v in tag_values:
        val = _encode_summary_value(tag, v)
        summary += _field(1, 2, _varint(len(val)) + val)
    body = _field(1, 1, struct.pack(
        "<d", time.time() if wall_time is None else wall_time))
    body += _field(2, 0, _varint(int(step)))
    if summary:
        body += _field(5, 2, _varint(len(summary)) + summary)
    return body


class SummaryWriter:
    """Minimal event-file writer (API subset of tensorboard's)."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.mxnet_tpu"
        self._f = open(os.path.join(logdir, fname), "wb")
        self._write_event(_encode_event(0, [], wall_time=time.time()))

    def _write_event(self, payload):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def add_scalar(self, tag, value, global_step=0):
        self._write_event(_encode_event(global_step, [(tag, value)]))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class LogMetricsCallback:
    """Batch-end callback streaming metrics to TensorBoard (reference:
    contrib/tensorboard.py:LogMetricsCallback)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)
