"""ONNX -> Symbol importer.

Reference: python/mxnet/contrib/onnx/onnx2mx/_op_translations.py +
import_onnx.py GraphProto. Builds a Symbol DAG + arg/aux param dicts from
a ModelProto; aux states are the BatchNormalization mean/var inputs, as
in the reference importer.
"""
from __future__ import annotations

import numpy as onp

from . import onnx_pb2 as O
from ...base import MXNetError

_ONNX_TO_DTYPE = {O.TensorProto.FLOAT: "float32",
                  O.TensorProto.DOUBLE: "float64",
                  O.TensorProto.FLOAT16: "float16",
                  O.TensorProto.BFLOAT16: "bfloat16",
                  O.TensorProto.UINT8: "uint8",
                  O.TensorProto.INT8: "int8",
                  O.TensorProto.INT32: "int32",
                  O.TensorProto.INT64: "int64",
                  O.TensorProto.BOOL: "bool"}


def _tensor_to_numpy(t):
    dtype = _ONNX_TO_DTYPE.get(t.data_type)
    if dtype is None:
        raise MXNetError(f"unsupported ONNX tensor dtype {t.data_type}")
    shape = tuple(t.dims)
    if t.raw_data:
        arr = onp.frombuffer(t.raw_data, dtype=onp.dtype(dtype)
                             if dtype != "bfloat16" else onp.uint16)
        if dtype == "bfloat16":
            arr = (arr.astype(onp.uint32) << 16).view(onp.float32)
    elif t.float_data:
        arr = onp.asarray(list(t.float_data), dtype=dtype)
    elif t.int64_data:
        arr = onp.asarray(list(t.int64_data), dtype=dtype)
    elif t.int32_data:
        arr = onp.asarray(list(t.int32_data), dtype=dtype)
    elif t.double_data:
        arr = onp.asarray(list(t.double_data), dtype=dtype)
    else:
        arr = onp.zeros(shape, dtype=dtype)
    return arr.reshape(shape)


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == O.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == O.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == O.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == O.AttributeProto.INTS:
            out[a.name] = tuple(int(x) for x in a.ints)
        elif a.type == O.AttributeProto.FLOATS:
            out[a.name] = tuple(float(x) for x in a.floats)
        elif a.type == O.AttributeProto.TENSOR:
            out[a.name] = _tensor_to_numpy(a.t)
        else:
            out[a.name] = None
    return out


def _split_pads(pads):
    """ONNX pads [b0,b1,...,e0,e1,...] -> symmetric mxnet pad or raise."""
    if not pads:
        return None
    n = len(pads) // 2
    before, after = pads[:n], pads[n:]
    if tuple(before) != tuple(after):
        raise MXNetError(f"asymmetric pads {pads} not supported")
    return tuple(before)


class _Importer:
    def __init__(self, model):
        from ... import symbol as sym

        self.sym = sym
        self.model = model
        self.tensors = {}  # onnx tensor name -> Symbol
        self.params = {}  # name -> numpy (initializers)
        self.aux_names = set()

    def const_value(self, name):
        """Numpy value of an initializer-backed tensor (for Reshape
        shapes, Clip bounds, ...)."""
        if name in self.params:
            return self.params[name]
        raise MXNetError(f"expected constant input '{name}'")

    def run(self):
        g = self.model.graph
        for t in g.initializer:
            self.params[t.name] = _tensor_to_numpy(t)
        for vi in g.input:
            if vi.name not in self.params:
                self.tensors[vi.name] = self.sym.var(vi.name)
        for node in g.node:
            fn = ONNX2MX_OPS.get(node.op_type)
            if fn is None:
                raise MXNetError(
                    f"ONNX op '{node.op_type}' has no import translation")
            fn(self, node, _attrs(node))
        outs = [self.tensors[o.name] for o in g.output]
        out = outs[0] if len(outs) == 1 else self.sym.Group(outs)
        from ... import ndarray as nd

        args, aux = {}, {}
        # params consumed as graph tensors become Variables lazily; only
        # those referenced by the built graph are returned
        used = {s._name for s in out._walk() if s._op is None}
        for name, arr in self.params.items():
            if name not in used:
                continue
            dst = aux if name in self.aux_names else args
            dst[name] = nd.array(arr)
        return out, args, aux

    def inp(self, name):
        """Symbol for a tensor name; initializers materialize as vars."""
        if name in self.tensors:
            return self.tensors[name]
        if name in self.params:
            v = self.sym.var(name)
            self.tensors[name] = v
            return v
        raise MXNetError(f"undefined tensor '{name}'")


ONNX2MX_OPS = {}


def register_import(*ops):
    def deco(fn):
        for n in ops:
            ONNX2MX_OPS[n] = fn
        return fn

    return deco


def _set(ctx, node, symbol):
    ctx.tensors[node.output[0]] = symbol


@register_import("Conv")
def _conv(ctx, node, attrs):
    ins = [ctx.inp(n) for n in node.input]
    w = ctx.const_value(node.input[1])
    ctx.tensors[node.output[0]] = ctx.sym.convolution(
        *ins,
        kernel=tuple(attrs.get("kernel_shape") or w.shape[2:]),
        stride=tuple(attrs.get("strides") or ()) or None,
        dilate=tuple(attrs.get("dilations") or ()) or None,
        pad=_split_pads(attrs.get("pads")),
        num_filter=int(w.shape[0]),
        num_group=int(attrs.get("group", 1)),
        no_bias=len(node.input) < 3,
        name=node.name or node.output[0])


@register_import("ConvTranspose")
def _deconv(ctx, node, attrs):
    ins = [ctx.inp(n) for n in node.input]
    w = ctx.const_value(node.input[1])
    ctx.tensors[node.output[0]] = ctx.sym.deconvolution(
        *ins,
        kernel=tuple(attrs.get("kernel_shape") or w.shape[2:]),
        stride=tuple(attrs.get("strides") or ()) or None,
        dilate=tuple(attrs.get("dilations") or ()) or None,
        pad=_split_pads(attrs.get("pads")),
        num_filter=int(w.shape[1]) * int(attrs.get("group", 1)),
        num_group=int(attrs.get("group", 1)),
        no_bias=len(node.input) < 3,
        name=node.name or node.output[0])


@register_import("BatchNormalization")
def _bn(ctx, node, attrs):
    ins = [ctx.inp(n) for n in node.input[:5]]
    ctx.aux_names.update(node.input[3:5])
    # ONNX BatchNormalization (single output) is inference mode: always
    # normalize with the running statistics, never batch stats
    _set(ctx, node, ctx.sym.batch_norm(
        *ins, eps=float(attrs.get("epsilon", 1e-5)),
        momentum=float(attrs.get("momentum", 0.9)),
        fix_gamma=False, use_global_stats=True, use_batch_stats=False,
        name=node.name or node.output[0]))


@register_import("Gemm")
def _gemm(ctx, node, attrs):
    if attrs.get("alpha", 1.0) != 1.0 or attrs.get("beta", 1.0) != 1.0 \
            or attrs.get("transA", 0):
        raise MXNetError("Gemm with alpha/beta/transA != defaults "
                         "not supported")
    w = ctx.const_value(node.input[1])
    if not attrs.get("transB", 0):
        # mxnet FC weight is (num_hidden, in); rewrite the initializer
        w = onp.ascontiguousarray(w.T)
        ctx.params[node.input[1]] = w
    ins = [ctx.inp(n) for n in node.input]
    _set(ctx, node, ctx.sym.fully_connected(
        *ins, num_hidden=int(w.shape[0]), no_bias=len(node.input) < 3,
        flatten=False, name=node.name or node.output[0]))


@register_import("MatMul")
def _matmul(ctx, node, attrs):
    a, b = (ctx.inp(n) for n in node.input[:2])
    _set(ctx, node, ctx.sym.dot(a, b, name=node.name or node.output[0]))


@register_import("MaxPool", "AveragePool")
def _pool(ctx, node, attrs):
    x = ctx.inp(node.input[0])
    kwargs = dict(
        kernel=tuple(attrs.get("kernel_shape") or ()),
        stride=tuple(attrs.get("strides") or ()) or None,
        pad=_split_pads(attrs.get("pads")),
        pool_type="max" if node.op_type == "MaxPool" else "avg",
        pooling_convention="full" if attrs.get("ceil_mode") else "valid")
    if node.op_type == "AveragePool":
        kwargs["count_include_pad"] = bool(
            attrs.get("count_include_pad", 1))
    _set(ctx, node, ctx.sym.pooling(
        x, name=node.name or node.output[0], **kwargs))


@register_import("GlobalMaxPool", "GlobalAveragePool")
def _gpool(ctx, node, attrs):
    x = ctx.inp(node.input[0])
    _set(ctx, node, ctx.sym.pooling(
        x, global_pool=True,
        pool_type="max" if "Max" in node.op_type else "avg",
        name=node.name or node.output[0]))


for _onnx, _act in [("Relu", "relu"), ("Sigmoid", "sigmoid"),
                    ("Tanh", "tanh"), ("Softplus", "softrelu"),
                    ("Softsign", "softsign")]:
    def _mk_act(act):
        def tr(ctx, node, attrs):
            _set(ctx, node, ctx.sym.activation(
                ctx.inp(node.input[0]), act_type=act,
                name=node.name or node.output[0]))
        return tr
    register_import(_onnx)(_mk_act(_act))


@register_import("LeakyRelu")
def _leaky(ctx, node, attrs):
    _set(ctx, node, ctx.sym.leaky_relu(
        ctx.inp(node.input[0]), act_type="leaky",
        slope=float(attrs.get("alpha", 0.01)),
        name=node.name or node.output[0]))


@register_import("Elu")
def _elu(ctx, node, attrs):
    _set(ctx, node, ctx.sym.leaky_relu(
        ctx.inp(node.input[0]), act_type="elu",
        slope=float(attrs.get("alpha", 1.0)),
        name=node.name or node.output[0]))


@register_import("Selu")
def _selu(ctx, node, attrs):
    _set(ctx, node, ctx.sym.leaky_relu(
        ctx.inp(node.input[0]), act_type="selu",
        name=node.name or node.output[0]))


@register_import("PRelu")
def _prelu(ctx, node, attrs):
    _set(ctx, node, ctx.sym.leaky_relu(
        ctx.inp(node.input[0]), ctx.inp(node.input[1]), act_type="prelu",
        name=node.name or node.output[0]))


@register_import("Flatten")
def _flatten(ctx, node, attrs):
    if int(attrs.get("axis", 1)) != 1:
        raise MXNetError("Flatten axis != 1 not supported")
    _set(ctx, node, ctx.sym.flatten(ctx.inp(node.input[0]),
                                    name=node.name or node.output[0]))


@register_import("Concat")
def _concat(ctx, node, attrs):
    ins = [ctx.inp(n) for n in node.input]
    _set(ctx, node, ctx.sym.concat(*ins, dim=int(attrs.get("axis", 1)),
                                   name=node.name or node.output[0]))


@register_import("Dropout")
def _dropout(ctx, node, attrs):
    p = attrs.get("ratio", 0.5)
    if len(node.input) > 1:
        p = float(onp.asarray(ctx.const_value(node.input[1])).reshape(()))
    _set(ctx, node, ctx.sym.dropout(ctx.inp(node.input[0]), p=p,
                                    name=node.name or node.output[0]))


@register_import("Softmax")
def _softmax(ctx, node, attrs):
    _set(ctx, node, ctx.sym.softmax(
        ctx.inp(node.input[0]), axis=int(attrs.get("axis", -1)),
        name=node.name or node.output[0]))


@register_import("LogSoftmax")
def _log_softmax(ctx, node, attrs):
    _set(ctx, node, ctx.sym.log_softmax(
        ctx.inp(node.input[0]), axis=int(attrs.get("axis", -1)),
        name=node.name or node.output[0]))


@register_import("Clip")
def _clip(ctx, node, attrs):
    lo, hi = attrs.get("min"), attrs.get("max")
    if len(node.input) > 1 and node.input[1]:
        lo = float(onp.asarray(ctx.const_value(node.input[1])).reshape(()))
    if len(node.input) > 2 and node.input[2]:
        hi = float(onp.asarray(ctx.const_value(node.input[2])).reshape(()))
    _set(ctx, node, ctx.sym.clip(
        ctx.inp(node.input[0]),
        a_min=lo if lo is not None else -3.4e38,
        a_max=hi if hi is not None else 3.4e38,
        name=node.name or node.output[0]))


@register_import("Reshape")
def _reshape(ctx, node, attrs):
    shape = attrs.get("shape")
    if shape is None:
        shape = tuple(int(x) for x in ctx.const_value(node.input[1]))
    _set(ctx, node, ctx.sym.reshape(ctx.inp(node.input[0]),
                                    shape=tuple(shape),
                                    name=node.name or node.output[0]))


@register_import("Transpose")
def _transpose(ctx, node, attrs):
    perm = attrs.get("perm")
    _set(ctx, node, ctx.sym.transpose(
        ctx.inp(node.input[0]),
        axes=tuple(perm) if perm else None,
        name=node.name or node.output[0]))


@register_import("Unsqueeze")
def _unsqueeze(ctx, node, attrs):
    axes = attrs.get("axes")
    if axes is None:
        axes = tuple(int(x) for x in ctx.const_value(node.input[1]))
    out = ctx.inp(node.input[0])
    for ax in sorted(axes):
        out = ctx.sym.expand_dims(out, axis=int(ax))
    ctx.tensors[node.output[0]] = out


@register_import("Squeeze")
def _squeeze(ctx, node, attrs):
    axes = attrs.get("axes")
    if axes is None and len(node.input) > 1:
        axes = tuple(int(x) for x in ctx.const_value(node.input[1]))
    _set(ctx, node, ctx.sym.squeeze(
        ctx.inp(node.input[0]),
        axis=tuple(axes) if axes else None,
        name=node.name or node.output[0]))


@register_import("Identity")
def _identity(ctx, node, attrs):
    ctx.tensors[node.output[0]] = ctx.inp(node.input[0])


_SCALAR_FOLD = {"broadcast_add": "broadcast_add_scalar",
                "broadcast_sub": "broadcast_sub_scalar",
                "broadcast_mul": "broadcast_mul_scalar",
                "broadcast_div": "broadcast_div_scalar",
                "broadcast_power": "broadcast_power_scalar",
                "broadcast_maximum": "maximum_scalar",
                "broadcast_minimum": "minimum_scalar"}


for _onnx, _mx in [("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                   ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
                   ("Pow", "broadcast_power"),
                   ("Max", "broadcast_maximum"),
                   ("Min", "broadcast_minimum")]:
    def _mk_bin(mxop):
        def tr(ctx, node, attrs):
            n0, n1 = node.input[:2]

            def scalar_const(n):
                """Fold initializer scalars back into op attrs — keeps
                re-imported graphs free of synthetic one-element params
                (reference onnx2mx does the same for broadcast ops)."""
                if n in ctx.params and n not in ctx.tensors:
                    v = onp.asarray(ctx.params[n])
                    if v.size == 1:
                        return float(v.reshape(()))
                return None

            sc = scalar_const(n1)
            scalar_op = _SCALAR_FOLD[mxop]
            if sc is not None:
                _set(ctx, node, getattr(ctx.sym, scalar_op)(
                    ctx.inp(n0), scalar=sc,
                    name=node.name or node.output[0]))
                return
            sc = scalar_const(n0)
            if sc is not None:
                _set(ctx, node, getattr(ctx.sym, scalar_op)(
                    ctx.inp(n1), scalar=sc, reverse=True,
                    name=node.name or node.output[0]))
                return
            a, b = ctx.inp(n0), ctx.inp(n1)
            _set(ctx, node, getattr(ctx.sym, mxop)(
                a, b, name=node.name or node.output[0]))
        return tr
    register_import(_onnx)(_mk_bin(_mx))


for _onnx, _mx in [("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"),
                   ("Abs", "abs"), ("Neg", "negative"),
                   ("Floor", "floor"), ("Ceil", "ceil"), ("Erf", "erf")]:
    def _mk_un(mxop):
        def tr(ctx, node, attrs):
            _set(ctx, node, getattr(ctx.sym, mxop)(
                ctx.inp(node.input[0]), name=node.name or node.output[0]))
        return tr
    register_import(_onnx)(_mk_un(_mx))


def _mk_reduce(mxop):
    def tr(ctx, node, attrs):
        axes = attrs.get("axes")
        if axes is None and len(node.input) > 1:
            axes = tuple(int(x) for x in ctx.const_value(node.input[1]))
        _set(ctx, node, getattr(ctx.sym, mxop)(
            ctx.inp(node.input[0]),
            axis=tuple(axes) if axes is not None else None,
            keepdims=bool(attrs.get("keepdims", 1)),
            name=node.name or node.output[0]))
    return tr


register_import("ReduceMean")(_mk_reduce("mean"))
register_import("ReduceSum")(_mk_reduce("sum"))
register_import("ReduceMax")(_mk_reduce("max"))
register_import("ReduceMin")(_mk_reduce("min"))
register_import("ReduceProd")(_mk_reduce("prod"))


@register_import("Sum")
def _sum_n(ctx, node, attrs):
    ins = [ctx.inp(n) for n in node.input]
    _set(ctx, node, ctx.sym.add_n(*ins, name=node.name or node.output[0]))


@register_import("LRN")
def _lrn(ctx, node, attrs):
    _set(ctx, node, ctx.sym.lrn(
        ctx.inp(node.input[0]), alpha=float(attrs.get("alpha", 1e-4)),
        beta=float(attrs.get("beta", 0.75)),
        knorm=float(attrs.get("bias", 2.0)),
        nsize=int(attrs.get("size", 5)),
        name=node.name or node.output[0]))


@register_import("Pad")
def _pad(ctx, node, attrs):
    pads = attrs.get("pads")
    if pads is None:
        pads = tuple(int(x) for x in ctx.const_value(node.input[1]))
    n = len(pads) // 2
    width = []
    for i in range(n):
        width += [pads[i], pads[n + i]]
    _set(ctx, node, ctx.sym.pad(
        ctx.inp(node.input[0]), mode=attrs.get("mode", "constant"),
        pad_width=tuple(width), name=node.name or node.output[0]))




# ---- round-5 breadth (mirrors mx2onnx additions; reference
# _op_translations.py import direction) ------------------------------------

for _onnx, _mx in [("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
                   ("Asin", "arcsin"), ("Acos", "arccos"),
                   ("Atan", "arctan"), ("Sinh", "sinh"), ("Cosh", "cosh"),
                   ("Round", "round"), ("Sign", "sign"),
                   ("Reciprocal", "reciprocal")]:
    def _mk_un2(mxop):
        def tr(ctx, node, attrs):
            _set(ctx, node, getattr(ctx.sym, mxop)(
                ctx.inp(node.input[0]), name=node.name or node.output[0]))
        return tr
    if _onnx not in ONNX2MX_OPS:
        register_import(_onnx)(_mk_un2(_mx))


for _onnx, _mx in [("Greater", "broadcast_greater"),
                   ("Less", "broadcast_lesser"),
                   ("Equal", "broadcast_equal"),
                   ("GreaterOrEqual", "broadcast_greater_equal"),
                   ("LessOrEqual", "broadcast_lesser_equal")]:
    def _mk_cmp(mxop):
        def tr(ctx, node, attrs):
            _set(ctx, node, getattr(ctx.sym, mxop)(
                ctx.inp(node.input[0]), ctx.inp(node.input[1]),
                name=node.name or node.output[0]))
        return tr
    register_import(_onnx)(_mk_cmp(_mx))


@register_import("Not")
def _not(ctx, node, attrs):
    x = ctx.inp(node.input[0])
    _set(ctx, node, ctx.sym.broadcast_equal(
        x, ctx.sym.zeros_like(x), name=node.name or node.output[0]))


@register_import("Where")
def _where(ctx, node, attrs):
    _set(ctx, node, ctx.sym.where(
        ctx.inp(node.input[0]), ctx.inp(node.input[1]),
        ctx.inp(node.input[2]), name=node.name or node.output[0]))


@register_import("Cast")
def _cast_imp(ctx, node, attrs):
    dt = _ONNX_TO_DTYPE.get(int(attrs.get("to", O.TensorProto.FLOAT)),
                            "float32")
    _set(ctx, node, ctx.sym.cast(ctx.inp(node.input[0]), dtype=dt,
                                 name=node.name or node.output[0]))


@register_import("Slice")
def _slice_imp(ctx, node, attrs):
    if "starts" in attrs:  # opset<10 attribute form
        starts = tuple(attrs["starts"])
        ends = tuple(attrs["ends"])
        axes = tuple(attrs.get("axes", range(len(starts))))
        steps = (1,) * len(starts)
    else:
        starts = tuple(int(x) for x in ctx.const_value(node.input[1]))
        ends = tuple(int(x) for x in ctx.const_value(node.input[2]))
        axes = tuple(int(x) for x in ctx.const_value(node.input[3])) \
            if len(node.input) > 3 else tuple(range(len(starts)))
        steps = tuple(int(x) for x in ctx.const_value(node.input[4])) \
            if len(node.input) > 4 else (1,) * len(starts)
    out = ctx.inp(node.input[0])
    big = 2 ** 31 - 1
    for ax, s, e, st in zip(axes, starts, ends, steps):
        if st != 1:
            raise MXNetError("Slice import supports step 1 only")
        out = ctx.sym.slice_axis(out, axis=int(ax), begin=int(s),
                                 end=None if e >= big else int(e))
    out._name = node.name or node.output[0]  # graft-lint: allow(L601)
    _set(ctx, node, out)


@register_import("Split")
def _split_imp(ctx, node, attrs):
    n = len(node.output)
    axis = int(attrs.get("axis", 0))
    sizes = attrs.get("split")
    if sizes is None and len(node.input) > 1 and node.input[1]:
        sizes = tuple(int(x) for x in ctx.const_value(node.input[1]))
    if sizes is not None and len(set(sizes)) > 1:
        # uneven split: slice_axis chain honoring the exact sizes
        start = 0
        for oname, sz in zip(node.output, sizes):
            ctx.tensors[oname] = ctx.sym.slice_axis(
                ctx.inp(node.input[0]), axis=axis, begin=start,
                end=start + int(sz))
            start += int(sz)
        return
    parts = ctx.sym.split(ctx.inp(node.input[0]), num_outputs=n,
                          axis=axis, name=node.name or node.output[0])
    for i, oname in enumerate(node.output):
        ctx.tensors[oname] = parts[i] if n > 1 else parts


@register_import("Gather")
def _gather(ctx, node, attrs):
    _set(ctx, node, ctx.sym.take(
        ctx.inp(node.input[0]), ctx.inp(node.input[1]),
        axis=int(attrs.get("axis", 0)),
        name=node.name or node.output[0]))


@register_import("GatherND")
def _gather_nd(ctx, node, attrs):
    # ONNX puts the index tuple on the LAST indices axis, mx gather_nd
    # on the FIRST — full-reverse transpose maps rank-2 indices exactly
    idx = ctx.sym.transpose(ctx.inp(node.input[1]))
    _set(ctx, node, ctx.sym.gather_nd(
        ctx.inp(node.input[0]), idx,
        name=node.name or node.output[0]))


@register_import("Tile")
def _tile_imp(ctx, node, attrs):
    reps = tuple(int(x) for x in ctx.const_value(node.input[1]))
    _set(ctx, node, ctx.sym.tile(ctx.inp(node.input[0]), reps=reps,
                                 name=node.name or node.output[0]))


@register_import("Expand")
def _expand(ctx, node, attrs):
    shape = tuple(int(x) for x in ctx.const_value(node.input[1]))
    _set(ctx, node, ctx.sym.broadcast_to(
        ctx.inp(node.input[0]), shape=shape,
        name=node.name or node.output[0]))


@register_import("Shape")
def _shape_imp(ctx, node, attrs):
    _set(ctx, node, ctx.sym.shape_array(
        ctx.inp(node.input[0]), name=node.name or node.output[0]))


@register_import("OneHot")
def _one_hot_imp(ctx, node, attrs):
    depth = int(onp.asarray(ctx.const_value(node.input[1])).reshape(()))
    vals = onp.asarray(ctx.const_value(node.input[2])).reshape(-1)
    _set(ctx, node, ctx.sym.one_hot(
        ctx.inp(node.input[0]), depth=depth,
        off_value=float(vals[0]), on_value=float(vals[1]),
        name=node.name or node.output[0]))


@register_import("ArgMax")
def _argmax_imp(ctx, node, attrs):
    _set(ctx, node, ctx.sym.argmax(
        ctx.inp(node.input[0]), axis=int(attrs.get("axis", 0)),
        keepdims=bool(attrs.get("keepdims", 1)),
        name=node.name or node.output[0]))


@register_import("ArgMin")
def _argmin_imp(ctx, node, attrs):
    _set(ctx, node, ctx.sym.argmin(
        ctx.inp(node.input[0]), axis=int(attrs.get("axis", 0)),
        keepdims=bool(attrs.get("keepdims", 1)),
        name=node.name or node.output[0]))


@register_import("TopK")
def _topk_imp(ctx, node, attrs):
    k = int(onp.asarray(ctx.const_value(node.input[1])).reshape(-1)[0])
    res = ctx.sym.topk(ctx.inp(node.input[0]), k=k,
                       axis=int(attrs.get("axis", -1)),
                       ret_typ="both",
                       is_ascend=not bool(attrs.get("largest", 1)),
                       name=node.name or node.output[0])
    ctx.tensors[node.output[0]] = res[0]
    if len(node.output) > 1:
        ctx.tensors[node.output[1]] = res[1]


@register_import("LayerNormalization")
def _layer_norm_imp(ctx, node, attrs):
    _set(ctx, node, ctx.sym.layer_norm(
        ctx.inp(node.input[0]), ctx.inp(node.input[1]),
        ctx.inp(node.input[2]), axis=int(attrs.get("axis", -1)),
        eps=float(attrs.get("epsilon", 1e-5)),
        name=node.name or node.output[0]))


@register_import("InstanceNormalization")
def _instance_norm_imp(ctx, node, attrs):
    _set(ctx, node, ctx.sym.instance_norm(
        ctx.inp(node.input[0]), ctx.inp(node.input[1]),
        ctx.inp(node.input[2]), eps=float(attrs.get("epsilon", 1e-3)),
        name=node.name or node.output[0]))


@register_import("ReduceL1")
def _reduce_l1(ctx, node, attrs):
    axes = attrs.get("axes")
    _set(ctx, node, ctx.sym.norm(
        ctx.inp(node.input[0]), ord=1,
        axis=tuple(axes) if axes else None,
        keepdims=bool(attrs.get("keepdims", 1)),
        name=node.name or node.output[0]))


@register_import("ReduceL2")
def _reduce_l2(ctx, node, attrs):
    axes = attrs.get("axes")
    _set(ctx, node, ctx.sym.norm(
        ctx.inp(node.input[0]), ord=2,
        axis=tuple(axes) if axes else None,
        keepdims=bool(attrs.get("keepdims", 1)),
        name=node.name or node.output[0]))


@register_import("DepthToSpace")
def _d2s(ctx, node, attrs):
    _set(ctx, node, ctx.sym.depth_to_space(
        ctx.inp(node.input[0]), block_size=int(attrs["blocksize"]),
        name=node.name or node.output[0]))


@register_import("SpaceToDepth")
def _s2d(ctx, node, attrs):
    _set(ctx, node, ctx.sym.space_to_depth(
        ctx.inp(node.input[0]), block_size=int(attrs["blocksize"]),
        name=node.name or node.output[0]))


@register_import("Resize")
def _resize(ctx, node, attrs):
    mode = attrs.get("mode", "nearest")
    if isinstance(mode, bytes):
        mode = mode.decode()
    if mode != "nearest":
        raise MXNetError(f"Resize import supports mode='nearest' only "
                         f"(got {mode!r})")
    scales = None
    if len(node.input) > 2 and node.input[2]:
        scales = onp.asarray(ctx.const_value(node.input[2])).reshape(-1)
    if scales is None or len(scales) != 4 or scales[2] != scales[3]:
        raise MXNetError("Resize import supports uniform HW scales only")
    if scales[0] != 1.0 or scales[1] != 1.0:
        raise MXNetError("Resize import cannot scale batch/channel dims")
    if float(scales[2]) != int(scales[2]):
        raise MXNetError(f"Resize import needs an integer HW scale "
                         f"(got {float(scales[2])})")
    _set(ctx, node, ctx.sym.UpSampling(
        ctx.inp(node.input[0]), scale=int(scales[2]),
        sample_type="nearest", name=node.name or node.output[0]))


@register_import("Constant")
def _constant(ctx, node, attrs):
    for a in node.attribute:
        if a.name == "value":
            ctx.params[node.output[0]] = _tensor_to_numpy(a.t)
            return
    raise MXNetError("Constant node without value tensor")


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params).

    Reference API: python/mxnet/contrib/onnx/onnx2mx/import_model.py."""
    with open(model_file, "rb") as f:
        model = O.ModelProto.FromString(f.read())
    return _Importer(model).run()


def get_model_metadata(model_file):
    """Reference: import_model.py get_model_metadata."""
    with open(model_file, "rb") as f:
        model = O.ModelProto.FromString(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def shapes(vis):
        out = []
        for vi in vis:
            if vi.name in inits:
                continue
            dims = tuple(d.dim_value for d in vi.type.tensor_type.shape.dim)
            out.append((vi.name, dims))
        return out

    return {"input_tensor_data": shapes(g.input),
            "output_tensor_data": shapes(g.output)}


def import_to_gluon(model_file, ctx=None):
    """Reference: contrib/onnx/onnx2mx/import_to_gluon.py."""
    from ...gluon import SymbolBlock
    from ... import symbol as _sym

    sym, args, aux = import_model(model_file)
    meta = get_model_metadata(model_file)
    inputs = [_sym.var(n) for n, _ in meta["input_tensor_data"]]
    net = SymbolBlock(sym, inputs)
    for name, p in net.collect_params().items():
        if name in args:
            p._load_init_from(args[name])
        elif name in aux:
            p._load_init_from(aux[name])
    return net
