"""Symbol+params -> ONNX exporter.

Reference: python/mxnet/contrib/onnx/mx2onnx/_op_translations.py (2082
LoC of per-op converters) + export_onnx.py MXNetGraph.create_onnx_graph_proto.
Same architecture — a registry of per-op translation functions walking
the symbol DAG — but emitting opset-13 graphs (Reshape/Clip/Dropout take
tensor operands instead of attrs) through the wire-compatible proto
subset in onnx_pb2.
"""
from __future__ import annotations

import numpy as onp

from . import onnx_pb2 as O
from ...base import MXNetError

_DTYPE_TO_ONNX = {"float32": O.TensorProto.FLOAT,
                  "float64": O.TensorProto.DOUBLE,
                  "float16": O.TensorProto.FLOAT16,
                  "bfloat16": O.TensorProto.BFLOAT16,
                  "uint8": O.TensorProto.UINT8,
                  "int8": O.TensorProto.INT8,
                  "int32": O.TensorProto.INT32,
                  "int64": O.TensorProto.INT64,
                  "bool": O.TensorProto.BOOL}

MX2ONNX_OPS = {}


def register_translator(*opnames):
    def deco(fn):
        for n in opnames:
            MX2ONNX_OPS[n] = fn
        return fn

    return deco


class GraphBuilder:
    def __init__(self, params):
        self.graph = O.GraphProto(name="mxnet_tpu_export")
        self.params = params  # name -> numpy
        self._initialized = set()
        self._n = 0
        # translators bump this when they emit ops newer than the
        # default opset; the model declares max(requested, min_opset)
        self.min_opset = 13

    def uniq(self, base):
        self._n += 1
        return f"{base}_{self._n}"

    def add_node(self, op_type, inputs, outputs, name=None, **attrs):
        node = self.graph.node.add(op_type=op_type,
                                   name=name or self.uniq(op_type.lower()))
        node.input.extend(inputs)
        node.output.extend(outputs)
        for k, v in attrs.items():
            if v is None:
                continue
            a = node.attribute.add(name=k)
            if isinstance(v, bool) or isinstance(v, int):
                a.type = O.AttributeProto.INT
                a.i = int(v)
            elif isinstance(v, float):
                a.type = O.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, str):
                a.type = O.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)):
                if v and isinstance(v[0], float):
                    a.type = O.AttributeProto.FLOATS
                    a.floats.extend(v)
                else:
                    a.type = O.AttributeProto.INTS
                    a.ints.extend(int(x) for x in v)
            else:
                raise MXNetError(f"unsupported attr {k}={v!r}")
        return node

    def add_initializer(self, name, arr):
        if name in self._initialized:
            return name
        arr = onp.ascontiguousarray(arr)
        t = self.graph.initializer.add(
            name=name, data_type=_DTYPE_TO_ONNX[str(arr.dtype)])
        t.dims.extend(arr.shape)
        t.raw_data = arr.tobytes()
        self._initialized.add(name)
        return name

    def const(self, base, arr):
        return self.add_initializer(self.uniq(base), onp.asarray(arr))


def _pads(pad):
    pad = tuple(pad or ())
    return list(pad) + list(pad) if pad else None


@register_translator("convolution")
def _conv(b, name, ins, attrs):
    b.add_node("Conv", ins, [name], name=name,
               kernel_shape=list(attrs.get("kernel") or ()),
               strides=list(attrs.get("stride") or ()) or None,
               dilations=list(attrs.get("dilate") or ()) or None,
               pads=_pads(attrs.get("pad")),
               group=int(attrs.get("num_group", 1)))


@register_translator("deconvolution")
def _deconv(b, name, ins, attrs):
    b.add_node("ConvTranspose", ins, [name], name=name,
               kernel_shape=list(attrs.get("kernel") or ()),
               strides=list(attrs.get("stride") or ()) or None,
               dilations=list(attrs.get("dilate") or ()) or None,
               pads=_pads(attrs.get("pad")),
               group=int(attrs.get("num_group", 1)))


@register_translator("batch_norm")
def _bn(b, name, ins, attrs):
    if int(attrs.get("axis", 1)) != 1:
        raise MXNetError("ONNX BatchNormalization is channel-axis-1 only")
    data, gamma, beta, mean, var = ins[:5]
    if attrs.get("fix_gamma", True):
        # the op ignores gamma when fix_gamma — bake all-ones so ONNX
        # semantics match (reference _op_translations.py convert_batchnorm)
        g = b.params.get(gamma)
        shape = g.shape if g is not None else b.params[beta].shape
        gamma = b.const(gamma + "_ones", onp.ones(shape, "float32"))
    b.add_node("BatchNormalization", [data, gamma, beta, mean, var],
               [name], name=name,
               epsilon=float(attrs.get("eps", 1e-3)),
               momentum=float(attrs.get("momentum", 0.9)))


@register_translator("fully_connected")
def _fc(b, name, ins, attrs):
    data = ins[0]
    if attrs.get("flatten", True):
        flat = b.uniq(name + "_flat")
        b.add_node("Flatten", [data], [flat], axis=1)
        data = flat
    b.add_node("Gemm", [data] + list(ins[1:]), [name], name=name,
               alpha=1.0, beta=1.0, transA=0, transB=1)


@register_translator("pooling")
def _pool(b, name, ins, attrs):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError(f"no ONNX global pool for '{ptype}'")
        b.add_node(op, ins, [name], name=name)
        return
    op = {"max": "MaxPool", "avg": "AveragePool"}.get(ptype)
    if op is None:
        raise MXNetError(f"no ONNX pool for '{ptype}'")
    extra = {}
    if ptype == "avg":
        extra["count_include_pad"] = int(
            attrs.get("count_include_pad", True))
    b.add_node(op, ins, [name], name=name,
               kernel_shape=list(attrs.get("kernel") or ()),
               strides=list(attrs.get("stride") or ()) or None,
               pads=_pads(attrs.get("pad")),
               ceil_mode=int(attrs.get("pooling_convention",
                                       "valid") == "full"),
               **extra)


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@register_translator("activation")
def _act(b, name, ins, attrs):
    act = attrs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"no ONNX op for act_type='{act}'")
    b.add_node(_ACT[act], ins, [name], name=name)


@register_translator("leaky_relu")
def _leaky(b, name, ins, attrs):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        b.add_node("LeakyRelu", ins[:1], [name], name=name,
                   alpha=float(attrs.get("slope", 0.25)))
    elif act == "elu":
        b.add_node("Elu", ins[:1], [name], name=name,
                   alpha=float(attrs.get("slope", 0.25)))
    elif act == "selu":
        b.add_node("Selu", ins[:1], [name], name=name)
    elif act == "prelu":
        b.add_node("PRelu", ins[:2], [name], name=name)
    elif act == "gelu":
        # Gelu is opset-20; at opset 13 emit the exact decomposition
        # 0.5 * x * (1 + erf(x / sqrt(2)))
        x = ins[0]
        scaled = b.uniq(name + "_sc")
        rt2 = b.const(name + "_rt2", onp.float32(2.0 ** 0.5))
        b.add_node("Div", [x, rt2], [scaled])
        erfed = b.uniq(name + "_erf")
        b.add_node("Erf", [scaled], [erfed])
        one = b.const(name + "_one", onp.float32(1.0))
        shifted = b.uniq(name + "_sh")
        b.add_node("Add", [erfed, one], [shifted])
        halfx = b.uniq(name + "_hx")
        half = b.const(name + "_half", onp.float32(0.5))
        b.add_node("Mul", [x, half], [halfx])
        b.add_node("Mul", [halfx, shifted], [name], name=name)
    else:
        raise MXNetError(f"no ONNX op for leaky_relu '{act}'")


@register_translator("flatten")
def _flatten(b, name, ins, attrs):
    b.add_node("Flatten", ins, [name], name=name, axis=1)


@register_translator("concat")
def _concat(b, name, ins, attrs):
    b.add_node("Concat", ins, [name], name=name,
               axis=int(attrs.get("dim", 1)))


@register_translator("dropout")
def _dropout(b, name, ins, attrs):
    ratio = b.const(name + "_ratio",
                    onp.float32(attrs.get("p", 0.5)))
    b.add_node("Dropout", [ins[0], ratio], [name], name=name)


@register_translator("softmax")
def _softmax(b, name, ins, attrs):
    b.add_node("Softmax", ins[:1], [name], name=name,
               axis=int(attrs.get("axis", -1)))


@register_translator("log_softmax")
def _log_softmax(b, name, ins, attrs):
    b.add_node("LogSoftmax", ins[:1], [name], name=name,
               axis=int(attrs.get("axis", -1)))


@register_translator("softmax_output")
def _softmax_output(b, name, ins, attrs):
    # inference semantics: plain softmax over the class axis
    b.add_node("Softmax", ins[:1], [name], name=name, axis=-1)


@register_translator("clip")
def _clip(b, name, ins, attrs):
    lo = b.const(name + "_min", onp.float32(attrs.get("a_min", 0.0)))
    hi = b.const(name + "_max", onp.float32(attrs.get("a_max", 0.0)))
    b.add_node("Clip", [ins[0], lo, hi], [name], name=name)


@register_translator("reshape")
def _reshape(b, name, ins, attrs):
    shape = list(attrs.get("shape") or ())
    if any(s in (-2, -3, -4) for s in shape):
        raise MXNetError("reshape special codes -2/-3/-4 not exportable")
    sh = b.const(name + "_shape", onp.asarray(shape, "int64"))
    b.add_node("Reshape", [ins[0], sh], [name], name=name)


@register_translator("transpose")
def _transpose(b, name, ins, attrs):
    axes = attrs.get("axes")
    b.add_node("Transpose", ins, [name], name=name,
               perm=list(axes) if axes else None)


@register_translator("expand_dims")
def _expand_dims(b, name, ins, attrs):
    ax = b.const(name + "_axes",
                 onp.asarray([attrs.get("axis", 0)], "int64"))
    b.add_node("Unsqueeze", [ins[0], ax], [name], name=name)


@register_translator("squeeze")
def _squeeze(b, name, ins, attrs):
    axis = attrs.get("axis")
    extra = []
    if axis is not None:
        if isinstance(axis, int):
            axis = [axis]
        extra = [b.const(name + "_axes", onp.asarray(axis, "int64"))]
    b.add_node("Squeeze", [ins[0]] + extra, [name], name=name)


for _mx, _onnx in [("broadcast_add", "Add"), ("elemwise_add", "Add"),
                   ("broadcast_sub", "Sub"), ("elemwise_sub", "Sub"),
                   ("broadcast_mul", "Mul"), ("elemwise_mul", "Mul"),
                   ("broadcast_div", "Div"), ("elemwise_div", "Div"),
                   ("broadcast_maximum", "Max"),
                   ("broadcast_minimum", "Min"),
                   ("broadcast_power", "Pow"),
                   ("relu", "Relu"), ("sigmoid", "Sigmoid"),
                   ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                   ("sqrt", "Sqrt"), ("abs", "Abs"), ("negative", "Neg"),
                   ("floor", "Floor"), ("ceil", "Ceil"),
                   ("erf", "Erf"), ("add_n", "Sum"), ("dot", "MatMul"),
                   ("batch_dot", "MatMul"), ("identity", "Identity"),
                   ("BlockGrad", "Identity"), ("make_loss", "Identity")]:
    def _mk(onnx_op):
        def tr(b, name, ins, attrs):
            b.add_node(onnx_op, ins, [name], name=name)
        return tr
    register_translator(_mx)(_mk(_onnx))


def _scalar_binop(onnx_op, rev_op=None):
    def tr(b, name, ins, attrs):
        c = b.const(name + "_scalar",
                    onp.float32(attrs.get("scalar", 0.0)))
        if attrs.get("reverse", False):
            b.add_node(rev_op or onnx_op, [c, ins[0]], [name], name=name)
        else:
            b.add_node(onnx_op, [ins[0], c], [name], name=name)
    return tr


for _mx, _onnx in [("_plus_scalar", "Add"), ("_minus_scalar", "Sub"),
                   ("_mul_scalar", "Mul"), ("_div_scalar", "Div"),
                   ("_power_scalar", "Pow"),
                   ("broadcast_add_scalar", "Add"),
                   ("broadcast_sub_scalar", "Sub"),
                   ("broadcast_mul_scalar", "Mul"),
                   ("broadcast_div_scalar", "Div"),
                   ("broadcast_power_scalar", "Pow"),
                   ("maximum_scalar", "Max"),
                   ("minimum_scalar", "Min")]:
    register_translator(_mx)(_scalar_binop(_onnx))


def _reduce(onnx_op):
    # at opset 13 ReduceMean takes axes as an ATTRIBUTE (input form is
    # opset 18+); ReduceSum-13 takes an axes input
    def tr(b, name, ins, attrs):
        axis = attrs.get("axis")
        if isinstance(axis, int):
            axis = [axis]
        kw = {"keepdims": int(attrs.get("keepdims", False))}
        extra = []
        if axis is not None:
            if onnx_op == "ReduceSum":
                extra = [b.const(name + "_axes",
                                 onp.asarray(axis, "int64"))]
            else:
                kw["axes"] = [int(a) for a in axis]
        b.add_node(onnx_op, [ins[0]] + extra, [name], name=name, **kw)
    return tr


register_translator("mean")(_reduce("ReduceMean"))
register_translator("sum")(_reduce("ReduceSum"))
register_translator("max")(_reduce("ReduceMax"))
register_translator("min")(_reduce("ReduceMin"))
register_translator("prod")(_reduce("ReduceProd"))


@register_translator("lrn")
def _lrn(b, name, ins, attrs):
    b.add_node("LRN", ins, [name], name=name,
               alpha=float(attrs.get("alpha", 1e-4)),
               beta=float(attrs.get("beta", 0.75)),
               bias=float(attrs.get("knorm", 2.0)),
               size=int(attrs.get("nsize", 5)))


@register_translator("pad")
def _pad(b, name, ins, attrs):
    width = attrs.get("pad_width") or ()
    # mxnet pad_width is (before0, after0, before1, after1, ...); onnx
    # wants all-befores then all-afters
    befores = list(width[0::2])
    afters = list(width[1::2])
    pads = b.const(name + "_pads", onp.asarray(befores + afters, "int64"))
    mode = attrs.get("mode", "constant")
    b.add_node("Pad", [ins[0], pads], [name], name=name,
               mode={"constant": "constant", "edge": "edge",
                     "reflect": "reflect"}[mode])


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path="model.onnx", verbose=False,
                 opset_version=13):
    """Export a Symbol (or symbol-json path) + params (dict or .params
    path, arg:/aux: prefixes accepted) to an ONNX file.

    Reference API: python/mxnet/contrib/onnx/mx2onnx/export_model.py.
    input_shape: tuple for the single input, or dict {input_name: shape}.
    Returns onnx_file_path.
    """
    from ... import symbol as _sym
    from ... import ndarray as _nd

    if isinstance(sym, str):
        sym = _sym.load(sym)
    if isinstance(params, str):
        params = _nd.load(params)
    nparams = {}
    for k, v in (params or {}).items():
        name = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        nparams[name] = v.asnumpy() if hasattr(v, "asnumpy") else \
            onp.asarray(v)
    if not isinstance(input_shape, dict):
        input_shape = {"data": tuple(input_shape)}

    b = GraphBuilder(nparams)
    # topo walk with output-view dedup (same canonicalization as tojson)
    seen = {}
    order = []
    for s in sym._walk():
        if s._group:  # Group wrapper is not a graph node
            continue
        if s._name not in seen:
            seen[s._name] = s
            order.append(s)

    def tensor_name(inp):
        base = inp._name
        node = seen[base]
        if node._num_outputs == 1:
            return base
        return f"{base}_out{inp._output_index}"

    for s in order:
        if s._op is None:
            if s._name in nparams:
                b.add_initializer(s._name, nparams[s._name])
            else:
                if s._name not in input_shape:
                    raise MXNetError(
                        f"free variable '{s._name}' has neither a param "
                        "value nor an entry in input_shape")
                vi = b.graph.input.add(name=s._name)
                tt = vi.type.tensor_type
                tt.elem_type = _DTYPE_TO_ONNX[str(input_type)]
                for d in input_shape[s._name]:
                    tt.shape.dim.add(dim_value=int(d))
            continue
        tr = MX2ONNX_OPS.get(s._op)
        if tr is None:
            raise MXNetError(
                f"op '{s._op}' has no ONNX translation "
                f"(reference parity list: _op_translations.py)")
        ins = [tensor_name(i) for i in s._inputs]
        if s._num_outputs == 1:
            tr(b, s._name, ins, s._kwargs)
        else:
            outs = [f"{s._name}_out{i}" for i in range(s._num_outputs)]
            tr_multi = getattr(tr, "multi", None)
            if tr_multi is None:
                raise MXNetError(
                    f"multi-output op '{s._op}' not exportable")
            tr_multi(b, s._name, ins, s._kwargs, outs)
        if verbose:
            print(f"[mx2onnx] {s._op} -> {s._name}")

    heads = sym._group or [sym]
    for h in heads:
        out = b.graph.output.add(name=tensor_name(h))
        out.type.tensor_type.elem_type = _DTYPE_TO_ONNX[str(input_type)]

    model = O.ModelProto(ir_version=7, producer_name="mxnet_tpu",
                         producer_version="3.0", graph=b.graph)
    model.opset_import.add(domain="",
                           version=max(opset_version, b.min_opset))
    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path


# ---- round-5 breadth (reference _op_translations.py tail) ----------------

for _mx, _onnx in [("sin", "Sin"), ("cos", "Cos"), ("tan", "Tan"),
                   ("arcsin", "Asin"), ("arccos", "Acos"),
                   ("arctan", "Atan"), ("sinh", "Sinh"), ("cosh", "Cosh"),
                   ("round", "Round"), ("sign", "Sign"),
                   ("reciprocal", "Reciprocal"),
                   ("depth_to_space", "DepthToSpace"),
                   ("space_to_depth", "SpaceToDepth")]:
    def _mk2(onnx_op):
        def tr(b, name, ins, attrs):
            kw = {}
            if onnx_op in ("DepthToSpace", "SpaceToDepth"):
                kw["blocksize"] = int(attrs.get("block_size", 2))
            b.add_node(onnx_op, ins, [name], name=name, **kw)
        return tr
    register_translator(_mx)(_mk2(_onnx))


@register_translator("where")
def _where_exp(b, name, ins, attrs):
    # mx conditions are float 0/1 masks; ONNX Where requires bool
    cond = b.uniq(name + "_cond")
    b.add_node("Cast", [ins[0]], [cond], to=int(O.TensorProto.BOOL))
    b.add_node("Where", [cond] + list(ins[1:]), [name], name=name)


@register_translator("gather_nd")
def _gather_nd_exp(b, name, ins, attrs):
    """mx gather_nd indices are (M, d1..dk) — index tuple on the LEADING
    axis; ONNX GatherND wants it on the LAST. The inserted full-reverse
    Transpose maps between them exactly for the rank-2 indices case
    (the only layout this exporter supports; reference
    _op_translations.py transposes the same way)."""
    idx = b.uniq(name + "_idxT")
    b.add_node("Transpose", [ins[1]], [idx])
    idx64 = b.uniq(name + "_idx64")
    b.add_node("Cast", [idx], [idx64], to=int(O.TensorProto.INT64))
    b.add_node("GatherND", [ins[0], idx64], [name], name=name)
    b.min_opset = max(b.min_opset, 12)


def _cmp_export(onnx_op):
    """mx comparisons return float 0/1 masks; ONNX returns bool — Cast
    back to float32 to keep graph semantics identical."""
    def tr(b, name, ins, attrs):
        raw = b.uniq(name + "_bool")
        b.add_node(onnx_op, ins, [raw])
        b.add_node("Cast", [raw], [name], name=name,
                   to=int(O.TensorProto.FLOAT))
    return tr


for _mx, _onnx in [("broadcast_greater", "Greater"),
                   ("broadcast_lesser", "Less"),
                   ("broadcast_equal", "Equal"),
                   ("broadcast_greater_equal", "GreaterOrEqual"),
                   ("broadcast_lesser_equal", "LessOrEqual"),
                   ("broadcast_not_equal", "Equal")]:
    if _mx == "broadcast_not_equal":
        def _ne(b, name, ins, attrs):
            eq = b.uniq(name + "_eq")
            b.add_node("Equal", ins, [eq])
            nb = b.uniq(name + "_not")
            b.add_node("Not", [eq], [nb])
            b.add_node("Cast", [nb], [name], name=name,
                       to=int(O.TensorProto.FLOAT))
        register_translator(_mx)(_ne)
    else:
        register_translator(_mx)(_cmp_export(_onnx))
# GreaterOrEqual/LessOrEqual exist from opset 12 (covered by default 13)


@register_translator("slice_axis")
def _slice_axis(b, name, ins, attrs):
    axis = int(attrs["axis"])
    begin = int(attrs.get("begin", 0) or 0)
    end = attrs.get("end")
    end = int(end) if end is not None else (2 ** 31 - 1)
    b.add_node("Slice",
               [ins[0], b.const(name + "_starts", onp.asarray([begin], "int64")),
                b.const(name + "_ends", onp.asarray([end], "int64")),
                b.const(name + "_axes", onp.asarray([axis], "int64"))],
               [name], name=name)


@register_translator("slice")
def _slice(b, name, ins, attrs):
    begin = [0 if v is None else int(v) for v in attrs.get("begin", ())]
    end = [(2 ** 31 - 1) if v is None else int(v)
           for v in attrs.get("end", ())]
    axes = list(range(len(begin)))
    extra = [b.const(name + "_starts", onp.asarray(begin, "int64")),
             b.const(name + "_ends", onp.asarray(end, "int64")),
             b.const(name + "_axes", onp.asarray(axes, "int64"))]
    step = attrs.get("step")
    if step:
        extra.append(b.const(name + "_steps", onp.asarray(
            [1 if v is None else int(v) for v in step], "int64")))
    b.add_node("Slice", [ins[0]] + extra, [name], name=name)


@register_translator("split")
def _split(b, name, ins, attrs):
    b.add_node("Split", ins, [name], name=name,
               axis=int(attrs.get("axis", 1)))


def _split_multi(b, name, ins, attrs, outs):
    b.add_node("Split", ins, outs, name=name,
               axis=int(attrs.get("axis", 1)))


_split.multi = _split_multi


@register_translator("embedding")
def _embedding(b, name, ins, attrs):
    idx = b.uniq(name + "_idx")
    b.add_node("Cast", [ins[0]], [idx], to=int(O.TensorProto.INT64))
    b.add_node("Gather", [ins[1], idx], [name], name=name, axis=0)


@register_translator("take")
def _take(b, name, ins, attrs):
    idx = b.uniq(name + "_idx")
    b.add_node("Cast", [ins[1]], [idx], to=int(O.TensorProto.INT64))
    b.add_node("Gather", [ins[0], idx], [name], name=name,
               axis=int(attrs.get("axis", 0)))


@register_translator("cast")
def _cast(b, name, ins, attrs):
    b.add_node("Cast", ins, [name], name=name,
               to=int(_DTYPE_TO_ONNX[str(attrs.get("dtype", "float32"))]))


@register_translator("tile")
def _tile(b, name, ins, attrs):
    reps = attrs.get("reps") or attrs.get("reps_", ())
    b.add_node("Tile",
               [ins[0], b.const(name + "_reps",
                                onp.asarray(list(reps), "int64"))],
               [name], name=name)


@register_translator("broadcast_to")
def _broadcast_to(b, name, ins, attrs):
    b.add_node("Expand",
               [ins[0], b.const(name + "_shape", onp.asarray(
                   list(attrs.get("shape", ())), "int64"))],
               [name], name=name)


@register_translator("shape_array")
def _shape_array(b, name, ins, attrs):
    b.add_node("Shape", ins, [name], name=name)


@register_translator("one_hot")
def _one_hot(b, name, ins, attrs):
    depth = int(attrs["depth"])
    on = float(attrs.get("on_value", 1.0))
    off = float(attrs.get("off_value", 0.0))
    idx = b.uniq(name + "_idx")
    b.add_node("Cast", [ins[0]], [idx], to=int(O.TensorProto.INT64))
    b.add_node("OneHot",
               [idx, b.const(name + "_depth", onp.asarray(depth, "int64")),
                b.const(name + "_vals", onp.asarray([off, on], "float32"))],
               [name], name=name, axis=-1)


@register_translator("argmax")
def _argmax(b, name, ins, attrs):
    raw = b.uniq(name + "_i64")
    axis = attrs.get("axis")
    data = ins[0]
    if axis is None:
        # axis=None flattens first (mx semantics: one flat index)
        flat = b.uniq(name + "_flat")
        b.add_node("Reshape",
                   [data, b.const(name + "_m1",
                                  onp.asarray([-1], "int64"))], [flat])
        data, axis = flat, 0
    b.add_node("ArgMax", [data], [raw], axis=int(axis),
               keepdims=int(attrs.get("keepdims", False)))
    b.add_node("Cast", [raw], [name], name=name,
               to=int(O.TensorProto.FLOAT))


@register_translator("argmin")
def _argmin(b, name, ins, attrs):
    raw = b.uniq(name + "_i64")
    axis = attrs.get("axis")
    data = ins[0]
    if axis is None:
        # axis=None flattens first (mx semantics: one flat index)
        flat = b.uniq(name + "_flat")
        b.add_node("Reshape",
                   [data, b.const(name + "_m1",
                                  onp.asarray([-1], "int64"))], [flat])
        data, axis = flat, 0
    b.add_node("ArgMin", [data], [raw], axis=int(axis),
               keepdims=int(attrs.get("keepdims", False)))
    b.add_node("Cast", [raw], [name], name=name,
               to=int(O.TensorProto.FLOAT))


@register_translator("topk")
def _topk(b, name, ins, attrs):
    raise MXNetError("ONNX TopK exports ret_typ='both' only")


def _topk_multi(b, name, ins, attrs, outs):
    if attrs.get("ret_typ", "indices") != "both":
        raise MXNetError("ONNX TopK exports ret_typ='both' only")
    k = int(attrs.get("k", 1))
    axis = int(attrs.get("axis", -1))
    idx_raw = b.uniq(name + "_idx64")
    b.add_node("TopK",
               [ins[0], b.const(name + "_k", onp.asarray([k], "int64"))],
               [outs[0], idx_raw], name=name, axis=axis,
               largest=int(not attrs.get("is_ascend", False)))
    b.add_node("Cast", [idx_raw], [outs[1]],
               to=int(O.TensorProto.FLOAT))


_topk.multi = _topk_multi


@register_translator("layer_norm")
def _layer_norm(b, name, ins, attrs):
    b.add_node("LayerNormalization", ins[:3], [name], name=name,
               axis=int(attrs.get("axis", -1)),
               epsilon=float(attrs.get("eps", 1e-5)))
    b.min_opset = max(b.min_opset, 17)  # LayerNormalization: opset >=17


@register_translator("instance_norm")
def _instance_norm(b, name, ins, attrs):
    b.add_node("InstanceNormalization", ins[:3], [name], name=name,
               epsilon=float(attrs.get("eps", 1e-3)))


@register_translator("norm")
def _norm(b, name, ins, attrs):
    ordv = int(attrs.get("ord", 2))
    axis = attrs.get("axis")
    kw = {"keepdims": int(attrs.get("keepdims", False))}
    if axis is not None:
        kw["axes"] = [axis] if isinstance(axis, int) else list(axis)
    op = {1: "ReduceL1", 2: "ReduceL2"}.get(ordv)
    if op is None:
        raise MXNetError(f"ONNX export supports norm ord 1/2, got {ordv}")
    b.add_node(op, ins, [name], name=name, **kw)


@register_translator("upsampling")
def _upsampling(b, name, ins, attrs):
    scale = float(attrs.get("scale", 2))
    b.add_node("Resize",
               [ins[0], b.const(name + "_roi", onp.asarray([], "float32")),
                b.const(name + "_scales",
                        onp.asarray([1.0, 1.0, scale, scale], "float32"))],
               [name], name=name, mode="nearest")


@register_translator("stack")
def _stack(b, name, ins, attrs):
    axis = int(attrs.get("axis", 0))
    unsq = []
    for i, x in enumerate(ins):
        u = b.uniq(f"{name}_u{i}")
        b.add_node("Unsqueeze",
                   [x, b.const(f"{name}_ax{i}",
                               onp.asarray([axis], "int64"))], [u])
        unsq.append(u)
    b.add_node("Concat", unsq, [name], name=name, axis=axis)
