"""ONNX interchange (reference: python/mxnet/contrib/onnx/__init__.py —
mx2onnx exporter + onnx2mx importer). Self-contained: the ONNX IR
messages are compiled from the wire-compatible schema subset in
``onnx.proto`` (no dependency on the onnx package)."""
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import (import_model, get_model_metadata,  # noqa: F401
                      import_to_gluon)

# reference module aliases (mx.contrib.onnx.mx2onnx / onnx2mx)
from . import mx2onnx, onnx2mx  # noqa: F401
