"""Chrome-trace / Perfetto JSON exporter.

One assembly point for everything the process knows about time:

- the tracer ring's span/instant events (``tracer.events()``),
- thread-name metadata (``"ph": "M"`` events, so Perfetto labels the
  batcher worker, DeviceFeed prefetcher, checkpoint writer threads by
  name instead of tid),
- one counter sample per registry family (``"ph": "C"``), named
  ``<family>/<counter>`` — the same legacy sample names
  ``profiler.dump()`` has always emitted (``eager_jit_cache/hits``,
  ``compile_cache/disk_hits``...), so existing trace consumers keep
  parsing,
- optionally, caller-supplied extra events — ``profiler.dump()`` passes
  its legacy ``_events`` list (Domain/Task/Frame scopes, ``record_op``
  dispatch events) so the two timelines land in ONE file.

The output is the Trace Event Format JSON array-of-dicts that
chrome://tracing and https://ui.perfetto.dev load directly:
``{"traceEvents": [{"name", "cat", "ph", "ts", "dur", "pid", "tid",
"args"}, ...], "displayTimeUnit": "ms"}``.
"""
from __future__ import annotations

import json

from . import tracer
from . import metrics as _metrics

__all__ = ["counter_samples", "thread_metadata", "build_trace",
           "dump_trace"]


def counter_samples(ts=None):
    """One ``"ph": "C"`` sample per numeric counter in every registry
    family, stamped at ``ts`` (µs; default: now on the tracer clock).
    Sample names are ``<family>/<counter>`` — the legacy
    ``profiler.dump()`` naming, kept verbatim."""
    _metrics._bootstrap_probes()
    if ts is None:
        import time

        ts = (time.monotonic() - tracer._EPOCH) * 1e6
    out = []
    for family, snap in _metrics.snapshot().items():
        for cname in sorted(snap):
            cval = snap[cname]
            if isinstance(cval, bool):
                cval = int(cval)
            if not isinstance(cval, (int, float)):
                continue
            out.append({"name": f"{family}/{cname}", "cat": "counter",
                        "ph": "C", "ts": ts, "pid": tracer._PID,
                        "args": {cname: cval}})
    return out


def thread_metadata():
    """``"ph": "M"`` thread_name events for every thread that emitted
    a span — Perfetto shows 'batcher-worker'/'prefetch-0' lanes."""
    return [{"name": "thread_name", "ph": "M", "pid": tracer._PID,
             "tid": tid, "args": {"name": name}}
            for tid, name in sorted(tracer.thread_names().items())]


def build_trace(extra_events=None, counters=True):
    """Assemble the full Chrome-trace payload dict (no IO).

    ``extra_events`` are appended verbatim (the profiler's legacy event
    list rides along here); ``counters=False`` skips the registry
    sample pass (the overhead bench times pure span export)."""
    events = thread_metadata()
    events.extend(tracer.events())
    if extra_events:
        events.extend(extra_events)
    if counters:
        events.extend(counter_samples())
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    dropped = tracer.dropped_spans()
    if dropped:
        payload["otherData"] = {"dropped_spans": dropped}
    return payload


def dump_trace(path, extra_events=None, counters=True):
    """Write the assembled trace to ``path`` and return the payload —
    ``json.load(open(path))`` round-trips, and the file opens directly
    in Perfetto / chrome://tracing."""
    payload = build_trace(extra_events=extra_events, counters=counters)
    with open(path, "w") as f:
        json.dump(payload, f)
    return payload
