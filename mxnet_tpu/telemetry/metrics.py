"""Unified metrics registry: every counter family in one place.

Before round 18 the process had ELEVEN disconnected counter surfaces:
ten ``profiler.*_counters()`` families (dispatch cache, fused step,
compile cache, pipeline, resilience, graph verify/opt, fusion,
sharding, serving) each backed by its own module-level dict + lock,
plus a serving-only Prometheus endpoint that exposed exactly one of
them. This module is the one registry they all live in now:

- **Owned families** (:class:`CounterFamily`): subsystems whose state
  IS a flat counter dict bind it here —
  ``_COUNTERS = telemetry.counter_family("pipeline", _zero_counters())``
  — and keep mutating it with the same ``_COUNTERS[name] += n`` code,
  now under the family's lock. graft_lint L901 enforces the discipline:
  a raw module-level counter dict mutated outside ``telemetry/`` is a
  lint error, so new counters can't regrow outside the registry.
- **Probed families** (:func:`register_family`): subsystems whose
  snapshot is computed (LRU ``.stats()``, latency quantiles, live
  gauges) register a snapshot callable instead; the registry calls it
  at read time, never on the hot path.

Both kinds surface identically: :func:`family_snapshot` /
:func:`snapshot` feed the ``profiler.*_counters()`` compatibility
views and the counter samples in ``profiler.dump()`` /
``telemetry.dump_trace``, and :func:`prometheus_text` renders ONE text
exposition — the serving histograms exactly as before (the serving
registry keeps its purpose-built exposition and plugs it in as a
block) plus every training-side family as ``mxnet_<family>_<name>``
gauges, scrapeable for the first time.
"""
from __future__ import annotations

from ..utils import locks as _locks

__all__ = ["labeled_lines",
           "CounterFamily", "MetricsRegistry", "REGISTRY",
           "counter_family", "register_family", "register_exposition",
           "family_snapshot", "snapshot", "prometheus_text"]


class CounterFamily:
    """A registry-owned, thread-safe, flat numeric counter dict.

    Implements the mapping slice the subsystems' counter code already
    uses (``[]``, ``get``, ``items``, ``clear``, iteration), so
    adopting the registry is a one-line binding change. Every mutation
    takes the family lock — the previous per-module locks moved here.
    """

    __slots__ = ("name", "_lock", "_zeros", "_data")

    def __init__(self, name, zeros=None):
        self.name = name
        # guards: _data
        self._lock = _locks.RankedLock("telemetry.counters")
        self._zeros = dict(zeros) if zeros else {}
        self._data = dict(self._zeros)

    # -- mutation (hot path: a lock + a couple of int ops) ------------

    def __setitem__(self, key, value):
        with self._lock:
            self._data[key] = value

    def add(self, key, delta=1):
        with self._lock:
            self._data[key] = self._data.get(key, 0) + delta

    def set(self, key, value):
        self[key] = value

    def clear(self):
        with self._lock:
            self._data.clear()

    def reset(self):
        """Back to the zero template (tests, benchmarks)."""
        with self._lock:
            self._data = dict(self._zeros)

    # -- reading ------------------------------------------------------

    def __getitem__(self, key):
        with self._lock:
            return self._data[key]

    def get(self, key, default=None):
        with self._lock:
            return self._data.get(key, default)

    def __contains__(self, key):
        with self._lock:
            return key in self._data

    def __iter__(self):
        return iter(self.snapshot())

    def __len__(self):
        with self._lock:
            return len(self._data)

    def items(self):
        return self.snapshot().items()

    def snapshot(self):
        with self._lock:
            return dict(self._data)


class MetricsRegistry:
    """Named counter/gauge families + pluggable Prometheus expositions.

    One process-wide instance (:data:`REGISTRY`); families register
    lazily at subsystem import, probes resolve at read time, and
    nothing here imports a subsystem — the registry must be importable
    before (and without) any of them."""

    def __init__(self):
        # guards: _owned, _probes, _expositions
        self._lock = _locks.RankedLock("telemetry.registry")
        self._owned = {}        # name -> CounterFamily
        self._probes = {}       # name -> callable() -> flat dict
        self._expositions = []  # (name, callable() -> prometheus text)

    def counter_family(self, name, zeros=None):
        """Create-or-fetch the owned family ``name``. Idempotent so a
        module reimport (tests) rebinds to the same live family."""
        with self._lock:
            fam = self._owned.get(name)
            if fam is None:
                fam = self._owned[name] = CounterFamily(name, zeros)
            return fam

    def register_family(self, name, probe):
        """Register (or replace) a probed family: ``probe()`` returns
        the family's flat numeric snapshot; it is called at read time
        only. Returns ``probe`` so import-time registration can
        decorate."""
        with self._lock:
            self._probes[name] = probe
        return probe

    def register_exposition(self, name, render):
        """Register a purpose-built Prometheus text block (the serving
        registry's histogram exposition) appended verbatim by
        :meth:`prometheus_text`. Idempotent by name."""
        with self._lock:
            self._expositions = [(n, r) for n, r in self._expositions
                                 if n != name]
            self._expositions.append((name, render))
        return render

    def families(self):
        """Public family names. A leading underscore marks an internal
        family (a sub-dict some probe already merges into its public
        view) — owned and mutable, but not separately surfaced."""
        with self._lock:
            return sorted(n for n in set(self._owned) | set(self._probes)
                          if not n.startswith("_"))

    def family_snapshot(self, name):
        """Flat numeric dict for one family ({} for unknown names —
        the profiler compatibility views must never raise). A probed
        family shadows an owned one of the same name: the probe is the
        richer, derived view."""
        with self._lock:
            probe = self._probes.get(name)
            fam = self._owned.get(name)
        if probe is not None:
            try:
                return dict(probe())
            except Exception:  # graft-lint: allow(L501)
                # a probe touching a half-torn-down subsystem (interp
                # shutdown) must not take the whole surface with it
                return {}
        return fam.snapshot() if fam is not None else {}

    def snapshot(self):
        """{family: {name: value}} across every registered family."""
        return {name: self.family_snapshot(name)
                for name in self.families()}

    # -- prometheus ---------------------------------------------------

    @staticmethod
    def _sanitize(name):
        out = []
        for ch in name:
            out.append(ch if ch.isalnum() or ch == "_" else "_")
        s = "".join(out)
        return s if not s[:1].isdigit() else "_" + s

    def prometheus_text(self):
        """ONE text exposition: every registered exposition block
        (serving's histograms/labels, exactly the pre-round-18 body),
        then every OTHER family as ``mxnet_<family>_<name>`` gauges.
        Families already covered by an exposition block are skipped —
        the serving counters must not appear twice under two names."""
        with self._lock:
            expositions = list(self._expositions)
        parts = []
        covered = set()
        for name, render in expositions:
            covered.add(name)
            try:
                parts.append(render().rstrip("\n"))
            except Exception:  # graft-lint: allow(L501)
                pass  # a broken block must not 500 the /metrics scrape
        for family in self.families():
            if family in covered:
                continue
            snap = self.family_snapshot(family)
            if not snap:
                continue
            fam_prefix = f"mxnet_{self._sanitize(family)}"
            lines = [f"# HELP {fam_prefix} {family} counters "
                     "(mxnet_tpu telemetry registry)",
                     f"# TYPE {fam_prefix} gauge"]
            for key in sorted(snap):
                val = snap[key]
                if isinstance(val, bool):
                    val = int(val)
                if not isinstance(val, (int, float)):
                    continue
                lines.append(
                    f"{fam_prefix}_{self._sanitize(key)} {val}")
            parts.append("\n".join(lines))
        return "\n".join(parts) + "\n"


#: the process-wide registry (module-level: importable before any
#: subsystem, and exactly one per process like serving's METRICS)
REGISTRY = MetricsRegistry()


def counter_family(name, zeros=None):
    """Module-level convenience for ``REGISTRY.counter_family``."""
    return REGISTRY.counter_family(name, zeros)


def register_family(name, probe):
    return REGISTRY.register_family(name, probe)


def register_exposition(name, render):
    return REGISTRY.register_exposition(name, render)


def family_snapshot(name):
    return REGISTRY.family_snapshot(name)


def snapshot():
    return REGISTRY.snapshot()


def prometheus_text():
    """The unified exposition (the serving ``/metrics`` body since
    round 18): serving histograms + every training-side family."""
    _bootstrap_probes()
    return REGISTRY.prometheus_text()


def labeled_lines(metric, rows, help_text=None):
    """Render one LABELED gauge metric as Prometheus text lines (round
    23: the fleet router's per-replica series). ``rows`` is an
    iterable of ``(labels_dict, value)``; returns ``[]`` when empty so
    an exposition block can concatenate unconditionally. Label values
    are escaped per the text-format rules (backslash, quote,
    newline); non-numeric values are skipped like the gauge pass."""
    rows = list(rows)
    if not rows:
        return []
    san = MetricsRegistry._sanitize
    name = f"mxnet_{san(metric)}"
    lines = [f"# HELP {name} {help_text or metric}",
             f"# TYPE {name} gauge"]
    for labels, value in rows:
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        lab = ",".join(
            '{}="{}"'.format(
                san(str(k)),
                str(v).replace("\\", r"\\").replace('"', r'\"')
                .replace("\n", r"\n"))
            for k, v in sorted(labels.items()))
        lines.append(f"{name}{{{lab}}} {value}")
    return lines


# -- probe bootstrap --------------------------------------------------------

# guards: _BOOTED
_BOOT_LOCK = _locks.RankedLock("telemetry.boot")
_BOOTED = False


def _bootstrap_probes():
    """Register the probed families whose owners are instance-based
    (LRU caches, the serving registry) or whose snapshot derives
    values. Lazy + idempotent: called before a full read surface
    (prometheus, dump_trace, snapshot-all), never at import — the
    registry must not drag jax in. Probes import inside try: a family
    whose subsystem can't import just reads empty."""
    global _BOOTED
    with _BOOT_LOCK:
        if _BOOTED:
            return
        _BOOTED = True

    def _probe(modpath, attr):
        def probe():
            import importlib

            mod = importlib.import_module(modpath)
            return getattr(mod, attr)()
        return probe

    for family, modpath, attr in (
            ("eager_jit_cache", "mxnet_tpu.ndarray.registry",
             "dispatch_cache_stats"),
            ("fused_step", "mxnet_tpu.gluon.fused_step",
             "fused_step_stats"),
            ("compile_cache", "mxnet_tpu.utils.compile_cache",
             "compile_cache_stats"),
            ("artifact", "mxnet_tpu.artifact", "artifact_stats"),
            ("serving", "mxnet_tpu.serving.metrics", "serving_stats"),
            ("pipeline", "mxnet_tpu.pipeline", "pipeline_counters"),
            ("resilience", "mxnet_tpu.resilience",
             "resilience_counters"),
            ("graph_verify", "mxnet_tpu.analysis", "counters"),
            ("graph_opt", "mxnet_tpu.analysis.graph_opt", "counters"),
            ("fusion", "mxnet_tpu.kernels", "counters"),
            ("sharding", "mxnet_tpu.sharding", "sharding_counters"),
    ):
        REGISTRY.register_family(family, _probe(modpath, attr))
    REGISTRY.register_exposition(
        "serving", _probe("mxnet_tpu.serving.metrics",
                          "prometheus_text"))
