"""Unified telemetry: span tracing + one metrics registry (round 18).

The observability layer the reference framework ships as its engine
profiler (``MXSetProfilerConfig`` / ``MXDumpProfile`` →
chrome://tracing), grown for the asynchronous stack rounds 11–17
built: nested/parallel spans make pipeline overlap and continuous
batching *visible*, a request-scoped trace id follows one HTTP request
through batcher/session/state-store threads, and every counter family
in the process — training and serving — reads and scrapes from one
registry.

Three pieces, importable à la carte:

- :mod:`.tracer` — ``span()`` / ``instant()`` / ``trace_context()``,
  ``MXNET_TELEMETRY={0,1,2}``-gated, bounded drop-oldest ring.
- :mod:`.metrics` — :class:`MetricsRegistry` (:data:`REGISTRY`):
  owned :class:`CounterFamily` dicts + probed families + ONE
  Prometheus exposition for training and serving.
- :mod:`.exporter` — ``dump_trace(path)``: Chrome-trace/Perfetto JSON
  of spans + thread names + registry counter samples.

``profiler`` keeps its MXNet-parity surface (``set_config`` /
``dump`` / ``dumps`` / ``*_counters()``) as thin views over this
package. This package imports nothing from the rest of ``mxnet_tpu``
at module level — it must be loadable before (and without) jax.

See ``docs/TELEMETRY.md``.
"""
from __future__ import annotations

from .tracer import (TELEMETRY_KNOB, buffer_capacity, current_trace_id,
                     dropped_spans, emit_span, events, instant, level,
                     new_trace_id, reset as reset_trace, span,
                     thread_names, trace_context, tracing)
from .metrics import (REGISTRY, CounterFamily, MetricsRegistry,
                      counter_family, family_snapshot, prometheus_text,
                      register_exposition, register_family, snapshot)
from .exporter import build_trace, counter_samples, dump_trace

__all__ = [
    # tracer
    "TELEMETRY_KNOB", "level", "tracing", "span", "instant",
    "emit_span", "trace_context", "current_trace_id", "new_trace_id",
    "events", "reset_trace", "dropped_spans", "buffer_capacity",
    "thread_names",
    # metrics
    "REGISTRY", "MetricsRegistry", "CounterFamily", "counter_family",
    "register_family", "register_exposition", "family_snapshot",
    "snapshot", "prometheus_text",
    # exporter
    "build_trace", "counter_samples", "dump_trace",
]
