"""Span tracer: the causal-timeline half of the telemetry subsystem.

Reference analog: MXNet's engine profiler (``MXSetProfilerConfig`` /
``MXDumpProfile``), which stamps every engine op into a chrome://tracing
timeline. Here the spans are host-side seams (dispatch, fused step,
pipeline stages, serving request lifecycle) — device compute is XLA's
and lives in the XPlane trace the profiler already drives — but the
contract is the same: nested/parallel spans with parent/child causality,
exportable to Perfetto.

Design constraints, in order:

1. **Zero-cost disabled.** ``MXNET_TELEMETRY=0`` (the default) must add
   nothing measurable to the eager-dispatch and fused-step hot loops:
   one env-dict lookup and an integer compare, no allocation, no lock.
   ``span(...)`` returns a shared no-op context manager.
2. **Never block the hot path.** The buffer is a bounded
   ``deque(maxlen=...)`` ring: appends are O(1), GIL-atomic, and when
   full the OLDEST span drops (a long-running server keeps its most
   recent window, like any flight recorder). Drops are counted
   (``dropped_spans``), never waited on.
3. **Causality.** Each thread keeps a span stack: a span opened inside
   another records it as parent, so the exported trace nests. Across
   threads — where a request's spans hop from the HTTP handler to the
   batcher worker — causality rides the **trace id** (request-scoped,
   propagated via :func:`trace_context` or an explicit ``trace_id=``
   argument), which every span stamps into its args.

Levels (``MXNET_TELEMETRY``): ``0`` off; ``1`` structural spans (step,
batch, request lifecycle, checkpoint, disk IO — a handful per step /
request); ``2`` adds high-frequency detail (per-op eager dispatch,
per-rewrite-pass spans). Levels gate at span creation, so a level-2
call site costs only the env read when the level is 1.

Clock: ``time.monotonic()`` everywhere (one clock across every thread;
serving deadline math already lives on it — graft_lint L602).
Timestamps are exported in microseconds relative to the tracer epoch.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

__all__ = ["TELEMETRY_KNOB", "level", "tracing", "span", "instant",
           "emit_span", "trace_context", "current_trace_id",
           "new_trace_id", "events", "reset", "dropped_spans",
           "buffer_capacity", "thread_names"]

TELEMETRY_KNOB = "MXNET_TELEMETRY"
_BUFFER_KNOB = "MXNET_TELEMETRY_BUFFER"
_DEFAULT_CAPACITY = 65536


def level():
    """``MXNET_TELEMETRY`` as an int (0 off / 1 structural / 2 verbose).
    Read per call — the hot-path cost of the disabled tracer IS this
    read, one dict lookup — so tests and benchmarks toggle it without
    reimport. Not routed through ``env.get_int`` on purpose: that
    helper logs on garbage, and this runs on every dispatch."""
    v = os.environ.get(TELEMETRY_KNOB)  # graft-lint: allow(L101)
    if not v:
        return 0
    try:
        return int(v)
    except ValueError:
        return 1  # a set-but-garbled knob means "on"


def tracing(need=1):
    """True when spans at detail level ``need`` are being recorded."""
    return level() >= need


class _Ring:
    """Bounded drop-oldest event ring. ``deque.append`` is GIL-atomic,
    so the hot path takes no lock; the emitted counter is a plain int
    (exact single-threaded, may undercount under heavy cross-thread
    races — it guards a diagnostic, not an invariant)."""

    __slots__ = ("buf", "emitted")

    def __init__(self, capacity):
        self.buf = deque(maxlen=int(capacity))
        self.emitted = 0

    @property
    def dropped(self):
        return max(0, self.emitted - len(self.buf))


def _capacity():
    try:
        cap = int(os.environ.get(  # graft-lint: allow(L101)
            _BUFFER_KNOB, _DEFAULT_CAPACITY))
    except ValueError:
        cap = _DEFAULT_CAPACITY
    return max(16, cap)


#: tracer epoch: every exported ts is monotonic-µs since this instant
_EPOCH = time.monotonic()
_RING = _Ring(_capacity())
_SPAN_IDS = itertools.count(1)  # next() is GIL-atomic
_THREADS = {}  # tid -> thread name, for exporter "M" metadata events
_PID = os.getpid()
_monotonic = time.monotonic  # hot-path local binding


class _TLState(threading.local):
    """Per-thread tracer state. The subclass ``__init__`` runs once per
    thread on first touch, so the hot path reads plain attributes — a
    bare ``threading.local`` pays an AttributeError-guarded ``getattr``
    on every span from a thread that never opened a trace context."""

    def __init__(self):
        self.stack = []  # open span ids (lexical nesting)
        self.trace = []  # trace-id stack (trace_context scopes)
        self.tid = ident = threading.get_ident() % 100000
        # assignment, not setdefault: the OS reuses idents of exited
        # threads, and the stale owner's name must not shadow the
        # thread currently holding the ident
        _THREADS[ident] = threading.current_thread().name


_TLS = _TLState()


def _tid():
    return _TLS.tid


def thread_names():
    """{tid: thread name} of every thread that touched the tracer."""
    return dict(_THREADS)


def _stack():
    return _TLS.stack


# -- trace-id propagation ---------------------------------------------------

def new_trace_id():
    """A fresh request-scoped trace id (hex, cheap, unique enough for
    correlating one process's spans with its HTTP responses)."""
    return f"{_PID & 0xffff:04x}{next(_SPAN_IDS) & 0xffffff:06x}" \
           f"{int((time.monotonic() - _EPOCH) * 1e6) & 0xffffff:06x}"


class _TraceCtx:
    __slots__ = ("trace_id",)

    def __init__(self, trace_id):
        self.trace_id = trace_id

    def __enter__(self):
        _TLS.trace.append(self.trace_id)
        return self.trace_id

    def __exit__(self, *exc):
        st = _TLS.trace
        if st:
            st.pop()


def trace_context(trace_id=None):
    """Scope the calling thread to ``trace_id`` (generated when None):
    every span/instant emitted inside — and only inside — stamps it.
    The id itself is returned by ``__enter__`` so the HTTP layer can
    echo it back to the client."""
    return _TraceCtx(trace_id or new_trace_id())


def current_trace_id():
    """The calling thread's active trace id, or None."""
    st = _TLS.trace
    return st[-1] if st else None


# -- span emission ----------------------------------------------------------

def _emit(ev):
    ring = _RING
    ring.buf.append(ev)
    ring.emitted += 1


def emit_span(name, cat, t0, t1, trace_id=None, parent=None, **attrs):
    """Record a completed span from explicit ``time.monotonic()``
    endpoints — for durations measured before the tracer gets involved
    (a request's queue wait runs from ``t_submit``, stamped in
    ``submit()``, to batch formation in a worker thread). Honors the
    ambient trace context when ``trace_id`` is not given. No level
    check: the caller gates (it usually already knows)."""
    args = attrs
    tid = trace_id if trace_id is not None else current_trace_id()
    if tid is not None:
        args["trace_id"] = tid
    if parent is not None:
        args["parent"] = parent
    _emit({"name": name, "cat": cat, "ph": "X",
           "ts": (t0 - _EPOCH) * 1e6,
           "dur": max(0.0, (t1 - t0) * 1e6),
           "pid": _PID, "tid": _tid(), "args": args})


def instant(name, cat="event", need=1, trace_id=None, **attrs):
    """An instant event ('i', thread-scoped) at detail level ``need``.
    No-op (one env read) below that level."""
    if level() < need:
        return
    args = attrs
    tid = trace_id if trace_id is not None else current_trace_id()
    if tid is not None:
        args["trace_id"] = tid
    stack = _stack()
    if stack:
        args["parent"] = stack[-1]
    _emit({"name": name, "cat": cat, "ph": "i", "s": "t",
           "ts": (time.monotonic() - _EPOCH) * 1e6,
           "pid": _PID, "tid": _tid(), "args": args})


class _NullSpan:
    """The disabled path: one shared instance, no state, no clocks."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        """No-op attr sink (mirrors _Span.set)."""


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "trace_id", "args", "_t0", "_id",
                 "_parent")

    def __init__(self, name, cat, trace_id, args):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.args = args

    def set(self, **attrs):
        """Attach attributes discovered mid-span (cache hit/miss,
        batch rows) to the span being recorded."""
        self.args.update(attrs)

    def __enter__(self):
        stack = _TLS.stack
        self._parent = stack[-1] if stack else None
        self._id = sid = next(_SPAN_IDS)
        stack.append(sid)
        self._t0 = _monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = _monotonic()
        tls = _TLS
        stack = tls.stack
        sid = self._id
        if stack and stack[-1] == sid:
            stack.pop()
        args = self.args
        args["span_id"] = sid
        if self._parent is not None:
            args["parent"] = self._parent
        tid = self.trace_id
        if tid is None:
            tr = tls.trace
            tid = tr[-1] if tr else None
        if tid is not None:
            args["trace_id"] = tid
        if exc_type is not None:
            args["error"] = exc_type.__name__
        ring = _RING
        ring.buf.append({"name": self.name, "cat": self.cat, "ph": "X",
                         "ts": (self._t0 - _EPOCH) * 1e6,
                         "dur": (t1 - self._t0) * 1e6,
                         "pid": _PID, "tid": tls.tid, "args": args})
        ring.emitted += 1
        return False


def span(name, cat="host", need=1, trace_id=None, **attrs):
    """The span context manager::

        with telemetry.span("serving.execute", cat="serving", rows=n):
            ...

    Below detail level ``need`` this returns a shared no-op — the
    disabled cost is the env read inside :func:`level`. Attributes are
    exported as the Chrome-trace event's ``args``; the ambient trace
    id (or an explicit ``trace_id=``) and the parent span id ride
    along, which is what makes one request's spans reconstructible
    across threads."""
    if level() < need:
        return _NULL
    return _Span(name, cat, trace_id, attrs)


# -- reading / lifecycle ----------------------------------------------------

def events():
    """Snapshot of the ring's events, oldest first (list copy; the
    ring keeps filling)."""
    return list(_RING.buf)


def dropped_spans():
    """Events evicted by ring wraparound since the last reset."""
    return _RING.dropped


def buffer_capacity():
    return _RING.buf.maxlen


def reset(capacity=None):
    """Drop all recorded events (tests, benchmarks); optionally resize
    the ring. Thread name registry survives — tids stay meaningful."""
    global _RING
    _RING = _Ring(capacity if capacity is not None else _capacity())
