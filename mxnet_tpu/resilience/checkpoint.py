"""CheckpointManager: crash-consistent snapshots of COMPLETE training
state.

``model.py``'s ``save_checkpoint`` (the reference parity surface) dumps
parameters only — a restart from it replays the optimizer from scratch,
re-draws different dropout masks, and forgets the AMP loss scale. This
manager snapshots everything a mid-epoch resume needs for BITWISE
parity with an uninterrupted run:

- parameters (creation order, by name),
- optimizer state (``Trainer._states``, the ``save_states`` tree),
- the update counters — ``num_update`` / ``begin_num_update`` /
  per-index counts / the AMP skip-step total (device-resident
  fused-step state is synced into the host mirrors first),
- the AMP :class:`LossScaler` (scale + grow-window position),
- the global PRNG stream position (``mxnet_tpu.random``), so dropout
  masks after a resume match the uninterrupted stream,
- kvstore contents (+ server-side updater state when present),
- the data cursor (epoch/step — whatever dict the caller passes;
  ``DeviceFeed.position`` feeds it).

**Crash consistency.** A checkpoint is a DIRECTORY written under a
temporary name and atomically renamed into place, carrying a
``manifest.json`` with per-file sha256 content hashes salted by the
framework/jax versions. A crash mid-write leaves only a ``.tmp-*``
directory (cleaned on the next save); a torn/corrupted/version-drifted
checkpoint fails hash validation and ``latest_valid`` falls back to
the previous good one with a warning — a restart NEVER loads a
half-written state (the property ps-lite servers get from applying
pushes transactionally; reference kvstore_dist_server.h).

**Async snapshots.** jax arrays are immutable, so *capturing* a
snapshot is just collecting references — plus device-side copies for
the buffers the fused step donates (``fused_step.state_copy``; donation
deletes the original even while Python references it). The
device→host transfer, pickling, hashing and file IO then run on a
background writer thread (``MXNET_CKPT_ASYNC``, default on), so the
step loop pays only the capture (benchmark/resilience_bench.py gates
the overhead at <5% of an epoch). ``wait()`` joins the writer; a
writer failure surfaces on the next ``save``/``wait``.

Retention: ``keep`` newest checkpoints are kept (``MXNET_CKPT_KEEP``,
default 3); older ones are pruned after each successful write.

**Sharded checkpoints (round 15).** When training runs under a
``sharding.plan_scope``, parameter and optimizer-state buffers live
sharded across the mesh. Saving gathers nothing: each non-replicated
device buffer becomes a placeholder in the main payload, and every
device's local shards land in a per-device ``shard-NNN.pkl`` file
(its own ``checkpoint_shard_write`` fault seam, same hash-manifested
atomic-rename discipline). The manifest's ``sharding`` section records
the mesh axes/shape and per-entry partition specs. Restore is
**mesh-shape agnostic**: the saved global index slices reassemble the
full host array regardless of the writer's mesh, so a checkpoint saved
on a 1x4 mesh restores onto 2x2, a single device, or any other shape
(``ckpt_reshards`` counts restores whose active mesh differs from the
writer's); under an active plan scope the restored buffers are placed
straight back at the plan's layouts.

**Serving session state (round 16).** ``session_state=`` attaches a
:class:`~mxnet_tpu.serving.state.SessionStateStore`: each save rides a
host snapshot of every live client's recurrent/KV state rows
(``export_state``), and ``restore`` re-opens those sessions into the
attached store (``restore_state``), so a server restart — or a canary
promote that hands the checkpoint to the successor — resumes mid-stream
decodes instead of dropping them.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import queue as _queue
import shutil
import threading
import time

from ..base import MXNetError

__all__ = ["CheckpointManager"]

FORMAT_VERSION = 1
_PAYLOAD = "state.pkl"
_MANIFEST = "manifest.json"
#: placeholder marker substituted for mesh-sharded buffers in the main
#: payload; ``load`` swaps the reassembled full array back in
_SHARD_REF = "__mxnet_shard_ref__"


def _log():
    return logging.getLogger(__name__)


def _salt():
    import jax

    from .. import __version__ as fw_version

    return [FORMAT_VERSION, fw_version, jax.__version__,
            jax.default_backend()]


def _hash(content, salt):
    """Version-salted content hash: a checkpoint written by a different
    framework/jax build fails validation instead of restoring state the
    new build would silently misinterpret."""
    h = hashlib.sha256()
    h.update(repr(salt).encode())
    h.update(content)
    return h.hexdigest()


def _is_device_array(x):
    import jax

    return isinstance(x, jax.Array)


_BULK_COPY = [None]


def _bulk_copy(arrays):
    """Device copies of a list of arrays in ONE compiled dispatch.

    Capture must copy every buffer the fused step will donate (holding
    a reference does not survive donation), and per-array ``jnp.array``
    calls cost ~0.2ms of dispatch each — the dominant step-thread cost
    of an async save. One jitted tree-copy pays one dispatch for the
    whole snapshot; jit caches per aval signature, so steady-state
    saves never retrace."""
    if not arrays:
        return []
    if _BULK_COPY[0] is None:
        import jax.numpy as jnp

        from ..utils import compile_cache as cc

        _BULK_COPY[0] = cc.counting_jit(
            lambda xs: tuple(jnp.array(x, copy=True) for x in xs),
            label="ckpt_bulk_copy")
    return list(_BULK_COPY[0](list(arrays)))


def _to_host(tree):
    """Device arrays -> numpy, recursively; everything else verbatim.
    Runs on the WRITER thread in async mode — the step loop never pays
    the D2H sync."""
    import numpy as onp

    if _is_device_array(tree):
        return onp.asarray(tree)
    if isinstance(tree, tuple):
        return tuple(_to_host(v) for v in tree)
    if isinstance(tree, list):
        return [_to_host(v) for v in tree]
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    return tree


class CheckpointManager:
    """Atomic, validated, keep-last-N checkpoint store (see module
    docstring).

    Parameters
    ----------
    directory : str, optional — checkpoint root (created if absent);
        default ``MXNET_CKPT_DIR`` or ``$MXNET_HOME/checkpoints``
    trainer : gluon.Trainer, optional — snapshots parameters +
        optimizer/scaler/counter state
    params : list of Parameter, optional — explicit parameter set
        (default: the trainer's)
    kvstore : KVStore, optional — snapshots store contents (+ updater
        state)
    keep : int — retention bound (default ``MXNET_CKPT_KEEP``)
    async_mode : bool — background writer thread (default
        ``MXNET_CKPT_ASYNC``)
    include_prng : bool — snapshot/restore the global PRNG stream
        position (default True; bitwise resume needs it whenever the
        forward draws keys — dropout, sampled ops)
    session_state : serving.SessionStateStore, optional — snapshots
        every live serving session's state rows and resumes them on
        restore (stateful continuous-batching serving)
    """

    def __init__(self, directory=None, trainer=None, params=None,
                 kvstore=None, keep=None, async_mode=None,
                 include_prng=True, session_state=None):
        from .. import env as _env

        if directory is None:
            directory = _env.get_str("MXNET_CKPT_DIR")
        if not directory:
            home = _env.get_str(
                "MXNET_HOME",
                os.path.join(os.path.expanduser("~"), ".mxnet"))
            directory = os.path.join(home, "checkpoints")
        self.directory = directory
        self.trainer = trainer
        self._params = params
        self.kvstore = kvstore
        self.keep = int(keep if keep is not None else
                        _env.get_int("MXNET_CKPT_KEEP", 3))
        self.async_mode = bool(
            async_mode if async_mode is not None else
            _env.get_bool("MXNET_CKPT_ASYNC", True))
        self.include_prng = bool(include_prng)
        self.session_state = session_state
        # one persistent writer thread over a BOUNDED job queue: the
        # step loop pays only the capture; serialize + IO overlap the
        # next steps, and a producer outrunning the writer blocks at
        # the bound instead of ballooning snapshots in memory
        self._q = None           # lazy: many managers never go async
        self._writer = None
        self._write_error = None
        os.makedirs(self.directory, exist_ok=True)
        self._clean_stale_tmp()

    # -- layout --------------------------------------------------------

    def _dir_for(self, step):
        return os.path.join(self.directory, f"ckpt-{int(step):012d}")

    def list_steps(self):
        """All checkpoint step numbers on disk (valid or not),
        ascending."""
        steps = []
        try:
            for name in os.listdir(self.directory):
                if name.startswith("ckpt-"):
                    try:
                        steps.append(int(name[5:]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return sorted(steps)

    def _clean_stale_tmp(self):
        """Remove half-written ``.tmp-*`` directories a crashed writer
        left behind (they are invisible to loads either way — cleanup
        just reclaims the disk)."""
        try:
            for name in os.listdir(self.directory):
                if name.startswith(".tmp-"):
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
        except OSError:
            pass

    # -- validation ----------------------------------------------------

    def validate(self, step):
        """True iff the checkpoint at ``step`` is complete and its
        content hashes (version-salted) match the manifest."""
        d = self._dir_for(step)
        try:
            with open(os.path.join(d, _MANIFEST)) as f:
                manifest = json.load(f)
            if manifest.get("format") != FORMAT_VERSION:
                return False
            salt = manifest.get("salt")
            if salt != _salt():
                return False
            for fname, info in manifest.get("files", {}).items():
                with open(os.path.join(d, fname), "rb") as f:
                    content = f.read()
                if len(content) != info.get("bytes") or \
                        _hash(content, salt) != info.get("sha256"):
                    return False
            return True
        except (OSError, ValueError, KeyError):
            return False

    def latest_valid(self):
        """The newest step whose checkpoint validates, or None. Invalid
        (torn/corrupt/version-drifted) checkpoints are skipped with a
        warning — the fallback the atomic-write discipline exists to
        guarantee."""
        from . import _count

        for step in reversed(self.list_steps()):
            if self.validate(step):
                return step
            _count("ckpt_corrupt_skipped")
            _log().warning(
                "checkpoint %s is corrupt or incomplete; falling back "
                "to the previous one", self._dir_for(step))
        return None

    # -- capture -------------------------------------------------------

    def _capture(self, step, cursor, extra):
        """Collect the full state tree NOW (device references + device
        copies of donated buffers + host scalars). Cheap — no D2H
        sync, no file IO — so async saves barely touch the step loop."""
        snap = {"step": int(step), "cursor": dict(cursor or {}),
                "extra": extra,
                "trainer": None, "params": None, "prng": None,
                "kvstore": None, "session_state": None}
        if self.session_state is not None:
            # already pure host primitives — the writer thread pickles
            # it unchanged, and a promote can hand it to the successor
            snap["session_state"] = self.session_state.export_state()
        trainer = self.trainer
        params = self._params
        if params is None and trainer is not None:
            params = trainer._params
        if trainer is not None:
            snap["trainer"] = self._capture_trainer(trainer)
        if params is not None:
            from .. import gluon  # noqa: F401 — Parameter lives there
            from ..gluon import fused_step as _fs

            live = [p for p in params
                    if getattr(p, "_ndarray", None) is not None]
            if _fs.donate_params_enabled():
                # donated buffers do not survive the next step: copy
                # (one bulk dispatch); plain refs suffice otherwise
                # (jax arrays are immutable)
                copies = _bulk_copy([p._ndarray._data for p in live])
                snap["params"] = [(p.name, c)
                                  for p, c in zip(live, copies)]
            else:
                snap["params"] = [(p.name, p._ndarray._data)
                                  for p in live]
        if self.include_prng:
            from .. import random as _mxrandom

            snap["prng"] = {"global_seed": _mxrandom._GLOBAL_SEED[0],
                            "key": _mxrandom._STATE.key}
        if self.kvstore is not None:
            snap["kvstore"] = self._capture_kvstore(self.kvstore)
        return snap

    @staticmethod
    def _capture_trainer(trainer):
        from .. import ndarray as nd
        from ..gluon import fused_step as _fs

        # in-flight async-grad-sync speculation must not leak across a
        # snapshot/restore boundary (the load_states round-trip rule)
        trainer._abandon_speculation()
        # device-resident fused-step state (skip-drifted update count,
        # loss scale) is authoritative — pull it into the host mirrors
        trainer._sync_fused_state()
        if not trainer._states_created:
            trainer._create_states()

        bufs = []

        def cap(v):
            if isinstance(v, nd.NDArray):
                # the fused step DONATES state buffers: a bare device
                # reference dies at the next step even while we hold
                # it — snapshot a device copy (one bulk dispatch for
                # the whole tree, filled in below)
                bufs.append(v.data)
                return ("nd", len(bufs) - 1)
            if isinstance(v, tuple):
                return ("tuple", tuple(cap(s) for s in v))
            return ("raw", v)

        def fill(v, copies):
            tag, val = v
            if tag == "nd":
                return ("nd", copies[val])
            if tag == "tuple":
                return ("tuple", tuple(fill(s, copies) for s in val))
            return v

        skeleton = [cap(s) for s in trainer._states]
        copies = _bulk_copy(bufs)
        optim = trainer._optimizer
        payload = {
            "num_update": optim.num_update,
            "begin_num_update": optim.begin_num_update,
            "index_update_count": dict(optim._index_update_count),
            "fused_skips": trainer._fused_skipped_steps(),
            "states": [fill(s, copies) for s in skeleton],
            "scaler": None,
        }
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler is not None:
            payload["scaler"] = {
                "loss_scale": scaler._loss_scale,
                "unskipped": scaler._unskipped,
                "scale_factor": scaler._scale_factor,
                "scale_window": scaler._scale_window}
        return payload

    @staticmethod
    def _capture_kvstore(kv):
        from ..ndarray import sparse as _sp

        if getattr(kv, "_async_mode", False):
            kv._async_flush()  # pending pushes must land in the snapshot
        values = {}
        for k, v in kv._store.items():
            if isinstance(v, _sp.BaseSparseNDArray):
                v = v.todense()
            values[k] = v.data
        updater_states = None
        updater = getattr(kv, "_updater", None)
        if updater is not None and hasattr(updater, "get_states"):
            updater_states = updater.get_states(dump_optimizer=False)
        return {"values": values, "updater_states": updater_states}

    # -- write ---------------------------------------------------------

    def save(self, step, cursor=None, extra=None):
        """Snapshot now; write inline (sync mode) or enqueue to the
        writer thread (async mode; at most two snapshots are in
        flight — a producer outrunning the writer blocks at the bound,
        counted as ``ckpt_async_waits``). Raises any pending writer
        failure. Returns the checkpoint directory path the write will
        land at."""
        from . import _count

        self._raise_pending()
        snap = self._capture(step, cursor, extra)
        if self.async_mode:
            q = self._ensure_writer()
            try:
                q.put_nowait(snap)
            except _queue.Full:
                _count("ckpt_async_waits")
                q.put(snap)
            _count("ckpt_async_saves")
        else:
            self._write(snap)
        return self._dir_for(step)

    def wait(self):
        """Block until every enqueued async write completed; re-raise
        the first failure."""
        if self._q is not None:
            self._q.join()
        self._raise_pending()

    def _ensure_writer(self):
        """The lazy persistent writer thread. It must NOT hold a strong
        reference to this manager: a dropped manager (and the trainer +
        parameters it carries) would otherwise be pinned by its own
        writer forever — the kvstore applier's weakref discipline. The
        finalizer posts the None sentinel that releases the thread."""
        if self._q is None:
            import weakref

            q = self._q = _queue.Queue(maxsize=2)
            ref = weakref.ref(self)

            def loop():
                while True:
                    snap = q.get()
                    try:
                        if snap is None:
                            return
                        mgr = ref()
                        if mgr is None:
                            return
                        try:
                            mgr._write(snap)
                        except BaseException as e:  # noqa: BLE001
                            # surfaced on the next save()/wait()
                            if mgr._write_error is None:
                                mgr._write_error = e
                        finally:
                            del mgr
                    finally:
                        q.task_done()

            self._writer = threading.Thread(
                target=loop, name="mxnet-ckpt-writer", daemon=True)
            self._writer.start()
            weakref.finalize(self, q.put, None)
        return self._q

    def _raise_pending(self):
        err, self._write_error = self._write_error, None
        if err is not None:
            raise MXNetError(
                f"background checkpoint write failed: {err}") from err

    @staticmethod
    def _extract_shards(snap):
        """Pull mesh-sharded buffers out of the snapshot tree.

        Returns ``(snap, shard_meta, shard_blobs)``: the tree with each
        non-replicated multi-device array replaced by a
        ``(_SHARD_REF, idx)`` placeholder, the manifest ``sharding``
        section, and ``{device_ordinal: [(idx, slices, np_shard), ...]}``
        — every device's LOCAL shards plus their global index slices,
        so restore reassembles the full array on ANY mesh shape.
        Replicated and single-device buffers stay in the main payload
        (no point writing N identical copies). ``(snap, None, {})``
        when nothing is sharded."""
        import numpy as onp

        entries, blobs, mesh_info = [], {}, [None]

        def sharded(x):
            if not _is_device_array(x):
                return False
            sh = getattr(x, "sharding", None)
            try:
                return (sh is not None and len(x.devices()) > 1
                        and not sh.is_fully_replicated)
            except Exception:  # noqa: BLE001 — exotic sharding types
                return False

        def walk(tree):
            if sharded(tree):
                idx = len(entries)
                sh = tree.sharding
                mesh = getattr(sh, "mesh", None)
                if mesh_info[0] is None and mesh is not None:
                    axes = dict(mesh.shape)
                    mesh_info[0] = {"axes": list(axes),
                                    "shape": [int(s)
                                              for s in axes.values()]}
                entries.append({
                    "idx": idx, "shape": [int(d) for d in tree.shape],
                    "dtype": str(tree.dtype),
                    "spec": repr(getattr(sh, "spec", None))})
                devs = sorted(d.id for d in tree.devices())
                ordinal = {d: i for i, d in enumerate(devs)}
                for s in tree.addressable_shards:
                    slices = [
                        [0 if sl.start is None else int(sl.start),
                         int(dim) if sl.stop is None else int(sl.stop)]
                        for sl, dim in zip(s.index, tree.shape)]
                    blobs.setdefault(ordinal[s.device.id], []).append(
                        (idx, slices, onp.asarray(s.data)))
                return (_SHARD_REF, idx)
            if isinstance(tree, tuple):
                return tuple(walk(v) for v in tree)
            if isinstance(tree, list):
                return [walk(v) for v in tree]
            if isinstance(tree, dict):
                return {k: walk(v) for k, v in tree.items()}
            return tree

        snap = walk(snap)
        if not entries:
            return snap, None, {}
        meta = {"mesh": mesh_info[0], "entries": entries,
                "shard_files": [f"shard-{di:03d}.pkl"
                                for di in sorted(blobs)]}
        return snap, meta, blobs

    def _write(self, snap):
        from . import _count
        from . import faults as _faults
        from .. import sharding as _sharding
        from ..telemetry import tracer as _telem

        with _telem.span("checkpoint.write", cat="checkpoint",
                         step=snap["step"],
                         mode="async" if self.async_mode else "sync"):
            self._write_inner(snap, _count, _faults, _sharding)

    def _write_inner(self, snap, _count, _faults, _sharding):
        t0 = time.perf_counter()
        _faults.maybe_fail("checkpoint_write")
        step = snap["step"]
        shard_meta, shard_blobs = None, {}
        if _sharding.sharding_enabled():
            snap, shard_meta, shard_blobs = self._extract_shards(snap)
        content = pickle.dumps(_to_host(snap),
                               protocol=pickle.HIGHEST_PROTOCOL)
        salt = _salt()
        files = {_PAYLOAD: content}
        for di in sorted(shard_blobs):
            files[f"shard-{di:03d}.pkl"] = pickle.dumps(
                shard_blobs[di], protocol=pickle.HIGHEST_PROTOCOL)
        manifest = {
            "format": FORMAT_VERSION, "salt": salt, "step": step,
            "cursor": snap["cursor"],
            "files": {name: {"sha256": _hash(blob, salt),
                             "bytes": len(blob)}
                      for name, blob in files.items()}}
        if shard_meta is not None:
            manifest["sharding"] = shard_meta
        final = self._dir_for(step)
        tmp = os.path.join(
            self.directory,
            f".tmp-ckpt-{step}-{os.getpid()}-{threading.get_ident()}")
        os.makedirs(tmp)
        try:
            for name, blob in files.items():
                if name != _PAYLOAD:
                    # registered fault point: one per-device shard file
                    # of a sharded checkpoint — a fire leaves only the
                    # .tmp-* dir, never a torn visible checkpoint
                    _faults.maybe_fail("checkpoint_shard_write")
                with open(os.path.join(tmp, name), "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):  # re-saving a step: replace whole
                shutil.rmtree(final)
            os.rename(tmp, final)  # atomic: a crash never exposes a torn dir
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _count("ckpt_saves")
        _count("ckpt_bytes", sum(len(b) for b in files.values()))
        _count("ckpt_write_s", time.perf_counter() - t0)
        if shard_meta is not None:
            _sharding._count("ckpt_sharded_saves")
            _sharding._count("ckpt_shard_files", len(files) - 1)
        self._prune()

    def _prune(self):
        from . import _count

        if self.keep <= 0:
            return
        steps = self.list_steps()
        for step in steps[:-self.keep]:
            shutil.rmtree(self._dir_for(step), ignore_errors=True)
            _count("ckpt_pruned")

    # -- restore -------------------------------------------------------

    def load(self, step=None):
        """The raw payload dict of a checkpoint (the latest valid one
        by default). Raises when none validates. A sharded checkpoint
        is reassembled to full host arrays here — regardless of the
        mesh (or absence of one) in THIS process."""
        if step is None:
            step = self.latest_valid()
            if step is None:
                raise MXNetError(
                    f"no valid checkpoint under {self.directory!r}")
        elif not self.validate(step):
            raise MXNetError(
                f"checkpoint {self._dir_for(step)!r} is missing or "
                "corrupt")
        d = self._dir_for(step)
        with open(os.path.join(d, _PAYLOAD), "rb") as f:
            payload = pickle.load(f)
        with open(os.path.join(d, _MANIFEST)) as f:
            shard_meta = json.load(f).get("sharding")
        if shard_meta is not None:
            payload = self._reassemble(d, payload, shard_meta)
        return payload

    @staticmethod
    def _reassemble(d, payload, meta):
        """Stitch per-device shard files back into full host arrays and
        substitute them for the payload's placeholders. The saved
        global index slices make this mesh-shape agnostic — the
        resharding-on-load half of the sharded-checkpoint contract
        (place back per plan happens in ``restore``)."""
        import numpy as onp

        from .. import sharding as _sharding

        full = {e["idx"]: onp.zeros(tuple(e["shape"]),
                                    dtype=e["dtype"])
                for e in meta["entries"]}
        for fname in meta["shard_files"]:
            with open(os.path.join(d, fname), "rb") as f:
                for idx, slices, arr in pickle.load(f):
                    full[idx][tuple(slice(a, b)
                                    for a, b in slices)] = arr

        def walk(tree):
            if isinstance(tree, tuple):
                if len(tree) == 2 and tree[0] == _SHARD_REF:
                    return full[tree[1]]
                return tuple(walk(v) for v in tree)
            if isinstance(tree, list):
                return [walk(v) for v in tree]
            if isinstance(tree, dict):
                return {k: walk(v) for k, v in tree.items()}
            return tree

        payload = walk(payload)
        _sharding._count("ckpt_sharded_restores")
        ctx = _sharding.current_plan()
        cur = None
        if ctx is not None:
            axes = dict(ctx[1].shape)
            cur = {"axes": list(axes),
                   "shape": [int(s) for s in axes.values()]}
        if cur != meta.get("mesh"):
            # restoring onto a different mesh shape (or none at all):
            # the writer's layout no longer exists — count the reshape
            _sharding._count("ckpt_reshards")
        return payload

    def restore(self, step=None):
        """Restore the latest valid (or given) checkpoint into the
        attached trainer/params/kvstore/PRNG. Returns ``{"step",
        "cursor", "extra"}`` so the caller can reposition its data
        pipeline. Any pending async write is joined first (restoring
        over a half-captured newer state would race the writer)."""
        from . import _count
        from ..telemetry import tracer as _telem

        with _telem.span("checkpoint.restore", cat="checkpoint") as _sp:
            out = self._restore_inner(step, _count)
            _sp.set(step=out["step"])
            return out

    def _restore_inner(self, step, _count):
        self.wait()
        payload = self.load(step)
        if payload.get("params") is not None:
            self._restore_params(payload["params"])
        if payload.get("trainer") is not None and self.trainer is not None:
            self._restore_trainer(self.trainer, payload["trainer"])
        if payload.get("prng") is not None and self.include_prng:
            import jax.numpy as jnp

            from .. import random as _mxrandom

            _mxrandom._GLOBAL_SEED[0] = payload["prng"]["global_seed"]
            _mxrandom._STATE.key = jnp.asarray(payload["prng"]["key"])
        if payload.get("kvstore") is not None and self.kvstore is not None:
            self._restore_kvstore(self.kvstore, payload["kvstore"])
        if payload.get("session_state") is not None and \
                self.session_state is not None:
            self.session_state.restore_state(payload["session_state"])
        self._replace_per_plan()
        _count("ckpt_restores")
        return {"step": payload["step"], "cursor": payload["cursor"],
                "extra": payload.get("extra")}

    def _replace_per_plan(self):
        """Under an active ``sharding.plan_scope``, put the restored
        (host-reassembled, single-device) parameter buffers straight
        back at the plan's layouts — the other half of
        resharding-on-load. Optimizer state re-places itself on the
        next fused step (``FusedShardCfg.place_args``); without a plan
        scope this is a no-op and buffers stay where ``nd.array`` put
        them."""
        from .. import sharding as _sharding

        ctx = _sharding.current_plan()
        if ctx is None:
            return
        params = self._params
        if params is None and self.trainer is not None:
            params = self.trainer._params
        if params is None:
            return
        _sharding.place_params(
            [(p.name, p) for p in params
             if getattr(p, "_ndarray", None) is not None],
            plan=ctx[0], mesh=ctx[1])

    def _restore_params(self, saved):
        params = self._params
        if params is None and self.trainer is not None:
            params = self.trainer._params
        if params is None:
            return
        by_name = {p.name: p for p in params}
        missing = [name for name, _ in saved if name not in by_name]
        if missing:
            raise MXNetError(
                "checkpoint parameters not present in the attached "
                f"group: {missing} (model/trainer mismatch?)")
        from .. import ndarray as nd
        from ..gluon import fused_step as _fs

        launder = _fs.donate_params_enabled()
        for name, val in saved:
            p = by_name[name]
            p._load_init_from(nd.array(val))
            if launder:
                # under MXNET_FUSED_STEP_DONATE param buffers are
                # donated too — same device_put-donation hazard as the
                # states (fused_step.state_adopt)
                import jax.numpy as jnp

                p._ndarray._data = jnp.array(p._ndarray._data,
                                             copy=True)

    @staticmethod
    def _restore_trainer(trainer, payload):
        from ..gluon import fused_step as _fs

        trainer._abandon_speculation()
        # shared walk (fused_step.state_tree_restore): rebuilds the
        # tagged tree with donation-safe (state_adopt'ed) buffers —
        # bitwise resume depends on not donating raw device_put
        # uploads to the fused step (jaxlib-0.4.37 CPU corruption)
        trainer._states = [_fs.state_tree_restore(s)
                           for s in payload["states"]]
        trainer._states_created = True
        optim = trainer._optimizer
        optim.num_update = payload["num_update"]
        optim.begin_num_update = payload["begin_num_update"]
        optim._index_update_count = dict(payload["index_update_count"])
        trainer._fused_skips_host = payload["fused_skips"]
        scaler_state = payload.get("scaler")
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler_state is not None and scaler is not None:
            scaler._loss_scale = float(scaler_state["loss_scale"])
            scaler._unskipped = int(scaler_state["unskipped"])
            # the grow schedule rides along: a resumed process whose
            # trainer was constructed with different scaler settings
            # must still replay the ORIGINAL run's episode exactly
            scaler._scale_factor = float(scaler_state["scale_factor"])
            scaler._scale_window = int(scaler_state["scale_window"])
        # device step-state is stale now; re-seed from the restored
        # host values on the next fused step
        trainer._invalidate_fused_state()

    @staticmethod
    def _restore_kvstore(kv, payload):
        from .. import ndarray as nd

        for k, val in payload["values"].items():
            arr = nd.array(val)
            stored = kv._store.get(k)
            if stored is None:
                kv._store[k] = arr
            else:
                stored._data = arr.data.astype(stored.data.dtype)
        states = payload.get("updater_states")
        updater = getattr(kv, "_updater", None)
        if states is not None and updater is not None and \
                hasattr(updater, "set_states"):
            updater.set_states(states)

    # -- lifecycle -----------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
