"""mxnet_tpu.resilience — fault-tolerant training and serving.

The availability layer of the framework (ROADMAP north star: "serves
heavy traffic from millions of users" — which means surviving the
failures a million-user fleet sees hourly). The reference inherits
ps-lite's core promise — long training jobs survive worker failure and
restart from server-held state (ps-lite Customer/Postoffice recovery,
kvstore_dist_server.h) — and this package rebuilds that promise
TPU-native, on top of the round-7/9/11 compiled training spine:

- :class:`~mxnet_tpu.resilience.checkpoint.CheckpointManager` —
  crash-consistent snapshots of COMPLETE training state (parameters,
  optimizer state, AMP loss-scaler, PRNG stream position, fused-step
  skip counters, kvstore contents, data cursor), written atomically
  (tmp dir + rename) under a manifest with version-salted content
  hashes, keep-last-N retention, and corrupt/partial detection that
  falls back to the last good checkpoint. An async mode serializes on
  a background writer thread off the step loop (jax arrays are
  immutable, so capturing device references IS a consistent snapshot —
  the host transfer and file IO then overlap the next steps).
- :class:`~mxnet_tpu.resilience.supervisor.AutoResume` — a training
  loop supervisor that catches faults, restores the last good
  checkpoint, and resumes at the exact step with bitwise parameter
  parity (identical loss traces vs an uninterrupted run, including
  through an AMP skip-step episode).
- :mod:`~mxnet_tpu.resilience.faults` — a deterministic
  fault-injection harness (``MXNET_FAULT_PLAN`` + programmatic API)
  with registered fault points at the real seams — ``device_put``
  staging, grad-bucket collective dispatch, kvstore push/pull, serving
  batch execution, compile-cache disk IO, engine push — firing by
  seeded step/count so every recovery path is exercisable in tier-1.
- :class:`~mxnet_tpu.resilience.retry.RetryPolicy` — the shared
  bounded-attempts, jittered-exponential-backoff policy (kvstore_ps
  transient sends route through it; terminal failures raise a clear
  :class:`~mxnet_tpu.resilience.retry.RetryExhausted`).
- :class:`~mxnet_tpu.resilience.breaker.CircuitBreaker` — serving-side
  degradation: a repeatedly-failing bucket executable trips back to
  the jit path (and ultimately open / fail-fast with cooldown), with
  the degraded state reflected in ``/healthz``.

``resilience_counters()`` surfaces checkpoint/restore/retry/breaker/
fault-fire counts; they ride ``profiler.dump()`` and the
``RESILIENCE`` runtime feature mirrors the master knob. See
docs/RESILIENCE.md.
"""
from __future__ import annotations

from ..telemetry import metrics as _telemetry

__all__ = ["CheckpointManager", "AutoResume", "ResumeExhausted",
           "RetryPolicy", "RetryExhausted", "CircuitBreaker",
           "CircuitOpen", "InjectedFault", "faults",
           "resilience_enabled", "resilience_counters",
           "reset_resilience_counters"]


def resilience_enabled():
    """MXNET_RESILIENCE master switch (default on). 0 degrades the
    subsystem to fail-fast semantics: retry policies make a single
    attempt, circuit breakers never trip, and AutoResume propagates
    the first fault instead of restoring. Checkpoint writes and the
    fault-injection harness are NOT gated (a disabled safety net must
    still let you take snapshots and run chaos drills). Read per use
    so tests can toggle without reimport."""
    from .. import env as _env

    return _env.get_bool("MXNET_RESILIENCE", True)


# ---------------------------------------------------------------------------
# counters (thread-safe: the checkpoint writer thread, serving workers,
# and the training thread all tick them). Registry-owned since round 18
# — same mutation idiom, unified Prometheus/trace-sample surface.


def _zero_counters():
    return {
        # checkpointing
        "ckpt_saves": 0,           # completed checkpoint writes
        "ckpt_async_saves": 0,     # of which rode the writer thread
        "ckpt_async_waits": 0,     # step loop blocked on a prior write
        "ckpt_write_s": 0.0,       # serialize+write wall time (writer)
        "ckpt_bytes": 0,           # payload bytes written
        "ckpt_restores": 0,        # successful restores
        "ckpt_corrupt_skipped": 0,  # invalid checkpoints skipped on load
        "ckpt_pruned": 0,          # retention-evicted checkpoints
        # auto-resume
        "resume_faults_caught": 0,  # step-loop faults the supervisor ate
        "resume_restarts": 0,       # restore-and-continue cycles
        # retry/backoff
        "retry_attempts": 0,       # EXTRA attempts beyond the first
        "retry_giveups": 0,        # policies that exhausted attempts
        "retry_sleep_s": 0.0,      # total backoff wall time
        # circuit breaker
        "breaker_trips": 0,        # closed -> open transitions
        "breaker_fast_fails": 0,   # calls rejected while open
        "breaker_resets": 0,       # half-open probe succeeded
        "breaker_demotions": 0,    # serving buckets demoted to jit path
        # fault injection
        "fault_fires": 0,          # injected faults raised (all points)
    }


_COUNTERS = _telemetry.counter_family("resilience", _zero_counters())


def _count(name, delta=1):
    _COUNTERS.add(name, delta)


def resilience_counters():
    """Live resilience counters, plus one ``fault_fires:<point>`` entry
    per fault point that fired and ``enabled`` mirroring the master
    knob (the profiler surface; see the module docstring)."""
    out = _COUNTERS.snapshot()
    from . import faults as _faults

    for point, n in _faults.fire_counts().items():
        out[f"fault_fires:{point}"] = n
    out["fault_armed"] = 1 if _faults.armed() else 0
    out["enabled"] = resilience_enabled()
    return out


def reset_resilience_counters():
    """Zero every counter (tests, benchmarks). Does not disarm an
    active fault plan — ``faults.disarm()`` owns that."""
    _COUNTERS.reset()
    from . import faults as _faults

    _faults.reset_fire_counts()


from . import faults  # noqa: E402
from .faults import InjectedFault  # noqa: E402
from .retry import RetryPolicy, RetryExhausted  # noqa: E402
from .breaker import CircuitBreaker, CircuitOpen  # noqa: E402
from .checkpoint import CheckpointManager  # noqa: E402
from .supervisor import AutoResume, ResumeExhausted  # noqa: E402
