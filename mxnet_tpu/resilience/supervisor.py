"""AutoResume: a fault-tolerant training-loop supervisor.

The round-11 async pipeline, the round-7 fused step and every loop
above them die unrecoverable on the first exception — one OOM, one
flaky storage read, one injected chaos fault ends the job. This
supervisor wraps the epoch/step loop with the restart discipline
ps-lite gave the reference (a failed worker rejoins and resumes from
server-held state; kvstore_dist_server.h):

- it takes a **step-0 checkpoint** before training (there is always a
  last good state to fall back to),
- checkpoints every ``ckpt_every`` steps through a
  :class:`~mxnet_tpu.resilience.checkpoint.CheckpointManager`
  (async by default — the write overlaps the next steps),
- **catches** step-loop faults (``catch``, default ``Exception``),
  restores the last good checkpoint — parameters, optimizer state,
  loss scaler, PRNG stream, kvstore, data cursor — and resumes at the
  EXACT step, up to ``max_restarts`` times
  (``MXNET_RESUME_MAX_RESTARTS``); past the budget it raises
  :class:`ResumeExhausted` chaining the last fault,
- survives **process death** the same way: a new process running the
  same ``AutoResume.run`` call restores the newest valid checkpoint
  and continues (the SIGKILL test in tests/test_resilience.py).

Bitwise contract: with a deterministic ``data_factory`` the final
parameters and the per-step loss trace of a crashed-and-resumed run
are IDENTICAL to an uninterrupted run — including through an AMP
skip-step episode — because the checkpoint captures the complete state
(see checkpoint.py) and replayed steps recompute from it. The loss
trace is keyed by global step, so steps replayed after a restore
overwrite their identical earlier values instead of duplicating.

With ``MXNET_RESILIENCE=0`` the supervisor still checkpoints but
propagates the first fault (fail-fast drills).
"""
from __future__ import annotations

import logging

from ..base import MXNetError

__all__ = ["AutoResume", "ResumeExhausted"]


class ResumeExhausted(MXNetError):
    """The restart budget ran out; chains the last underlying fault."""

    def __init__(self, message, restarts=0):
        super().__init__(message)
        self.restarts = restarts


class AutoResume:
    """Supervised training loop over a CheckpointManager.

    Parameters
    ----------
    manager : CheckpointManager — carries the trainer/params/kvstore
        to snapshot and restore
    data_factory : callable(epoch) -> iterable of batches. MUST be
        deterministic per epoch (the resume replays an epoch's prefix
        by skipping already-consumed batches).
    step_fn : callable(batch) -> loss (an NDArray/float, recorded in
        the trace) or None. Runs forward/backward/``trainer.step``.
    epochs : int — total epochs to run
    ckpt_every : int — checkpoint every N global steps (default 50);
        0 disables periodic saves (only step-0 + final remain)
    catch : exception type(s) treated as recoverable step faults
    max_restarts : int — restore-and-continue budget (default
        ``MXNET_RESUME_MAX_RESTARTS``)
    on_restore : callable(cursor dict), optional — hook after each
        restore (re-open readers, reset external services)
    final_save : bool — write a final checkpoint when training
        completes (default True)
    """

    def __init__(self, manager, data_factory, step_fn, epochs=1,
                 ckpt_every=50, catch=(Exception,), max_restarts=None,
                 on_restore=None, final_save=True):
        from .. import env as _env

        self.manager = manager
        self.data_factory = data_factory
        self.step_fn = step_fn
        self.epochs = int(epochs)
        self.ckpt_every = int(ckpt_every)
        self.catch = catch if isinstance(catch, tuple) else (catch,)
        self.max_restarts = int(
            max_restarts if max_restarts is not None else
            _env.get_int("MXNET_RESUME_MAX_RESTARTS", 3))
        self.on_restore = on_restore
        self.final_save = bool(final_save)
        self.restarts = 0
        self.losses = {}  # global step -> loss (replays overwrite)
        self._last_step = 0

    # -- the supervised loop -------------------------------------------

    def run(self):
        """Run (or resume) training to completion. Returns the ordered
        loss trace (one entry per global step)."""
        from . import _count, resilience_enabled

        cursor = self._initial_cursor()
        while True:
            try:
                self._train_from(cursor)
                break
            except self.catch as e:  # noqa: PERF203 — the supervisor
                _count("resume_faults_caught")
                if not resilience_enabled():
                    raise
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise ResumeExhausted(
                        f"training fault survived {self.max_restarts} "
                        "restart(s) and recurred; giving up "
                        f"(last fault: {type(e).__name__}: {e})",
                        restarts=self.restarts) from e
                logging.getLogger(__name__).warning(
                    "training fault (%s: %s); restoring last good "
                    "checkpoint (restart %d/%d)", type(e).__name__, e,
                    self.restarts, self.max_restarts)
                cursor = self._restore()
                _count("resume_restarts")
        if self.final_save:
            self._save(self.epochs, 0, self._last_step)
            self.manager.wait()
        return [self.losses[s] for s in sorted(self.losses)]

    def _save(self, epoch, step, g):
        """One supervised checkpoint: cursor + the loss trace so far.
        The trace rides the checkpoint's ``extra`` payload — a resumed
        PROCESS (not just a resumed loop) then reports the identical
        full trace, not only its own tail. Copied at capture time: the
        async writer pickles later, while steps keep appending."""
        self.manager.save(g, cursor={"epoch": epoch,
                                     "step_in_epoch": step,
                                     "global_step": g},
                          extra={"losses": dict(self.losses)})

    def _initial_cursor(self):
        """Resume point: the newest valid checkpoint if one exists
        (process restart), else a fresh step-0 checkpoint (so a fault
        before the first periodic save still has a fallback)."""
        if self.manager.latest_valid() is not None:
            return self._restore()
        self._last_step = 0
        self._save(0, 0, 0)
        self.manager.wait()  # the fallback must EXIST before training
        return {"epoch": 0, "step_in_epoch": 0, "global_step": 0}

    def _restore(self):
        meta = self.manager.restore()
        cursor = meta["cursor"] or {}
        cursor.setdefault("epoch", 0)
        cursor.setdefault("step_in_epoch", 0)
        cursor.setdefault("global_step", 0)
        extra = meta.get("extra") or {}
        if "losses" in extra:
            # a fresh process resumes with the FULL trace history
            self.losses = {int(k): v
                           for k, v in extra["losses"].items()}
        # the trace beyond the checkpoint belongs to the aborted
        # attempt; replayed steps will rewrite it identically
        g = cursor["global_step"]
        for s in [s for s in self.losses if s >= g]:
            del self.losses[s]
        self._last_step = g
        if self.on_restore is not None:
            self.on_restore(cursor)
        return cursor

    def _train_from(self, cursor):
        epoch0 = int(cursor.get("epoch", 0))
        skip = int(cursor.get("step_in_epoch", 0))
        g = int(cursor.get("global_step", 0))
        for epoch in range(epoch0, self.epochs):
            it = iter(self.data_factory(epoch))
            step = 0
            if epoch == epoch0 and skip:
                # replay the epoch prefix the checkpoint already
                # consumed: pull and DISCARD (the factory is
                # deterministic, so batch k is batch k again)
                for _ in range(skip):
                    next(it)
                step = skip
            for batch in it:
                loss = self.step_fn(batch)
                if loss is not None:
                    self.losses[g] = loss
                step += 1
                g += 1
                self._last_step = g
                if self.ckpt_every > 0 and g % self.ckpt_every == 0:
                    self._save(epoch, step, g)
            skip = 0
