"""Shared retry/backoff policy.

Transient-failure handling was previously ad hoc (kvstore_ps failed a
push on the first send error; pull loops hand-rolled their own sleeps).
This module is the ONE policy every retrying seam routes through —
bounded attempts, jittered exponential backoff, a clear terminal error
— so behavior and counters are uniform (reference analog: ps-lite's
van resend/timeout machinery, configured once, used by every
connection).

Defaults come from the knob registry (``MXNET_RETRY_MAX_ATTEMPTS``,
``MXNET_RETRY_BACKOFF_MS``, ``MXNET_RETRY_BACKOFF_MAX_MS``); with the
``MXNET_RESILIENCE`` master switch off a policy makes exactly one
attempt (fail-fast semantics). Jitter is decorrelated-uniform: delay =
``base * 2**(attempt-1)`` scaled by a uniform draw in ``[1-jitter, 1]``
— seeded policies draw deterministically (tests assert exact backoff
sequences)."""
from __future__ import annotations

import logging
import random as _pyrandom
import time

from ..base import MXNetError

__all__ = ["RetryPolicy", "RetryExhausted"]


class RetryExhausted(MXNetError):
    """Terminal retry failure: all attempts failed. Chains the last
    underlying exception (``raise ... from last``) and carries
    ``attempts`` so callers/operators see exactly what was tried."""

    def __init__(self, message, attempts=0):
        super().__init__(message)
        self.attempts = attempts


class RetryPolicy:
    """Bounded-attempt, jittered-exponential-backoff retry runner.

    Parameters (None = the env-knob default)
    ----------
    max_attempts : int — TOTAL attempts including the first (so 1 =
        no retries); forced to 1 when ``MXNET_RESILIENCE=0``
    base_ms / max_ms : float — backoff starts at ``base_ms`` and
        doubles per retry, capped at ``max_ms``
    jitter : float in [0, 1] — each delay is scaled by a uniform draw
        in ``[1 - jitter, 1]`` (0 = deterministic full backoff)
    retry_on : exception type(s) considered transient; anything else
        propagates immediately
    seed : int — deterministic jitter stream (tests); default draws
        from the process RNG
    name : str — labels log lines and terminal errors
    sleep : callable — injectable clock (tests); default ``time.sleep``
    """

    def __init__(self, max_attempts=None, base_ms=None, max_ms=None,
                 jitter=0.5, retry_on=(Exception,), seed=None,
                 name="retry", sleep=None):
        from .. import env as _env

        self.max_attempts = int(
            max_attempts if max_attempts is not None else
            _env.get_int("MXNET_RETRY_MAX_ATTEMPTS", 4))
        self.base_ms = float(
            base_ms if base_ms is not None else
            _env.get_float("MXNET_RETRY_BACKOFF_MS", 50.0))
        self.max_ms = float(
            max_ms if max_ms is not None else
            _env.get_float("MXNET_RETRY_BACKOFF_MAX_MS", 2000.0))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self.retry_on = retry_on if isinstance(retry_on, tuple) \
            else (retry_on,)
        self.name = name
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = _pyrandom.Random(seed) if seed is not None \
            else _pyrandom

    def delay_ms(self, attempt):
        """Backoff before retry number ``attempt`` (1-based)."""
        raw = min(self.max_ms, self.base_ms * (2.0 ** (attempt - 1)))
        if self.jitter:
            raw *= 1.0 - self.jitter * self._rng.random()
        return raw

    def run(self, fn, *args, **kwargs):
        """Call ``fn(*args, **kwargs)``, retrying transient failures.
        Returns the first successful result; raises
        :class:`RetryExhausted` (chaining the last failure) when every
        attempt failed, or the original exception immediately when it
        is not in ``retry_on``."""
        from . import _count, resilience_enabled

        attempts = self.max_attempts if resilience_enabled() else 1
        attempts = max(1, attempts)
        last = None
        for attempt in range(1, attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:  # transient: back off and retry
                last = e
                if attempt >= attempts:
                    break
                delay = self.delay_ms(attempt) / 1e3
                _count("retry_attempts")
                _count("retry_sleep_s", delay)
                logging.getLogger(__name__).debug(
                    "%s: attempt %d/%d failed (%s); retrying in %.0fms",
                    self.name, attempt, attempts, e, delay * 1e3)
                if delay > 0:
                    self._sleep(delay)
        _count("retry_giveups")
        raise RetryExhausted(
            f"{self.name}: all {attempts} attempt(s) failed "
            f"(last error: {type(last).__name__}: {last})",
            attempts=attempts) from last

    def wrap(self, fn):
        """Decorator form of :meth:`run`."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.run(fn, *args, **kwargs)

        return wrapped
