"""Deterministic fault-injection harness.

Every recovery path in this package is only trustworthy if it can be
EXERCISED — on demand, deterministically, in tier-1 — so the framework
registers **fault points** at its real failure seams and this module
decides, per call, whether to raise there. The reference ecosystem
tests ps-lite recovery by killing real processes; that stays the
gold-standard test (tests/test_resilience.py does it with SIGKILL), but
a seeded in-process harness makes EVERY seam reachable cheaply.

Registered fault points (the catalogue; ``FAULT_POINTS``):

========================  ==================================================
``device_put``            DeviceFeed staging of a batch leaf onto the
                          device (pipeline/device_feed.py) — models a
                          failed H2D transfer / OOM during prefetch
``grad_bucket_dispatch``  an AsyncGradReducer bucket collective dispatch
                          mid-backward (pipeline/grad_sync.py)
``kvstore_push``          KVStore.push / AsyncParamServer.push — a lost
                          or failed gradient send
``kvstore_pull``          KVStore.pull — a failed parameter fetch
``serving_execute``       one InferenceSession bucket execution on the
                          serving request path (serving/session.py)
``compile_cache_io``      persistent compile-cache disk IO
                          (utils/compile_cache.py load/store)
``engine_push``           scheduling a host task on the dependency
                          engine (engine.py)
``checkpoint_write``      serializing/writing a checkpoint payload
                          (resilience/checkpoint.py)
``checkpoint_shard_write``  one per-device shard file write of a
                          plan-sharded checkpoint
                          (resilience/checkpoint.py) — a fire mid-way
                          leaves only the ``.tmp-*`` dir (atomicity)
``serving_admission``     the admission-control decision at submit()
                          (serving/admission.py) — a fire forces the
                          shed path for sheddable SLO classes
``model_swap``            ModelRepository's atomic version activation
                          (serving/repository.py first deploy /
                          promote) — a fire aborts the swap, leaving
                          the incumbent active; rollback is
                          deliberately seam-free (it must always
                          succeed)
``session_state_evict``   SessionStateStore.acquire on the decode path
                          (serving/state.py) — a fire evicts the
                          acquiring session's state slot and raises
                          ``SessionEvicted``, so the blast radius of a
                          mid-stream eviction is exactly one client
========================  ==================================================

A **plan** maps fault points to firing clauses. From the environment::

    MXNET_FAULT_PLAN="device_put:at=3;kvstore_push:every=5:times=2"

or programmatically::

    from mxnet_tpu.resilience import faults
    faults.arm("device_put:at=3")            # or a {point: spec} dict
    ...
    faults.disarm()

    with faults.inject("kvstore_push", every=2, times=3, exc=OSError):
        ...

Clause keys (all integers unless noted): ``at=N`` fire on the Nth call
to the point (1-based, once); ``every=N`` fire on every Nth call;
``prob=P`` (float) fire with probability P from a ``random.Random``
seeded by ``seed`` (default ``MXNET_FAULT_SEED``) folded with the point
name — deterministic per (seed, point, call sequence); ``after=N``
ignore the first N calls; ``times=K`` cap total fires (default 1 for
``at``, unlimited otherwise); ``exc=Name`` the exception type to raise
(``InjectedFault`` by default; OSError/IOError/RuntimeError/ValueError/
ConnectionError/TimeoutError/MXNetError by name).

The disarmed fast path is one module-global ``is None`` check, so the
seams cost nothing in production. Arming an unknown point raises (a
typo'd plan that silently never fires is worse than no plan).
"""
from __future__ import annotations

import random as _pyrandom
import zlib

from ..base import MXNetError
from ..utils import locks as _locks

__all__ = ["InjectedFault", "FAULT_POINTS", "register_fault_point",
           "maybe_fail", "arm", "disarm", "inject", "armed",
           "fire_counts", "reset_fire_counts", "parse_plan"]


class InjectedFault(MXNetError, OSError):
    """The default injected exception. Subclasses both MXNetError (so
    framework-error handlers see it) and OSError (so IO-seam handlers
    that narrowly catch OSError exercise their real recovery path)."""


#: name -> one-line description; the catalogue docs/RESILIENCE.md
#: renders and ``arm`` validates against.
FAULT_POINTS = {
    "device_put": "DeviceFeed H2D staging of a batch leaf",
    "grad_bucket_dispatch": "async grad-sync bucket collective dispatch",
    "kvstore_push": "kvstore gradient push (local + param-server send)",
    "kvstore_pull": "kvstore parameter pull",
    "serving_execute": "InferenceSession bucket execution",
    "compile_cache_io": "persistent compile-cache disk load/store",
    "engine_push": "dependency-engine host-task push",
    "checkpoint_write": "checkpoint payload serialize/write",
    "checkpoint_shard_write": "per-device shard file write of a "
                              "plan-sharded checkpoint",
    "serving_admission": "admission-control decision (forces the shed "
                         "path for sheddable classes)",
    "model_swap": "ModelRepository atomic version activation "
                  "(first deploy / promote; rollback is seam-free)",
    "session_state_evict": "SessionStateStore slot acquire on the "
                           "decode path (a fire evicts the acquiring "
                           "session, surfacing SessionEvicted to "
                           "exactly that one client)",
    "autotune_measure": "autotune candidate measurement (a fire skips "
                        "that candidate; the sweep degrades to the "
                        "remaining ones instead of crashing)",
}

_EXC_BY_NAME = {
    "InjectedFault": InjectedFault, "MXNetError": MXNetError,
    "OSError": OSError, "IOError": OSError, "RuntimeError": RuntimeError,
    "ValueError": ValueError, "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
}


def register_fault_point(name, description):
    """Extension point: declare a new fault point (custom subsystems,
    tests). Idempotent for an identical description."""
    FAULT_POINTS[str(name)] = str(description)


class _Clause:
    """One point's firing rule + its mutable call/fire counters.
    Ticked under the module lock — fault points sit on multi-threaded
    seams (feed workers, serving workers, the writer thread)."""

    __slots__ = ("point", "at", "every", "prob", "after", "times",
                 "exc", "calls", "fires", "_rng")

    def __init__(self, point, at=None, every=None, prob=None, after=0,
                 times=None, exc=InjectedFault, seed=None):
        if at is None and every is None and prob is None:
            raise MXNetError(
                f"fault clause for {point!r} needs a trigger "
                "(at=N | every=N | prob=P)")
        self.point = point
        self.at = None if at is None else int(at)
        self.every = None if every is None else max(1, int(every))
        self.prob = None if prob is None else float(prob)
        self.after = int(after or 0)
        if times is None:
            times = 1 if self.at is not None else None
        self.times = None if times is None else int(times)
        self.exc = exc
        self.calls = 0
        self.fires = 0
        if self.prob is not None:
            if seed is None:
                from .. import env as _env

                seed = _env.get_int("MXNET_FAULT_SEED", 0)
            # fold the point name in so two probabilistic clauses under
            # one seed draw DIFFERENT (but each deterministic) streams;
            # crc32, not hash(): PYTHONHASHSEED randomizes str hashes
            # per process, and the firing sequence must be reproducible
            # across runs (the whole point of a SEEDED plan)
            self._rng = _pyrandom.Random(
                (int(seed) << 32) ^ zlib.crc32(point.encode()))
        else:
            self._rng = None

    def should_fire(self):
        """Advance the call counter; True when this call must raise."""
        self.calls += 1
        n = self.calls
        if n <= self.after:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        if self.at is not None:
            hit = n == self.at
        elif self.every is not None:
            hit = (n - self.after) % self.every == 0
        else:
            hit = self._rng.random() < self.prob
        if hit:
            self.fires += 1
        return hit


# guards: _PLAN, _FIRES
_LOCK = _locks.RankedLock("resilience.faults")
_PLAN = None          # dict point -> _Clause, or None (disarmed)
_FIRES = {}           # point -> total fires across plans (counters)


def parse_plan(spec, seed=None):
    """``MXNET_FAULT_PLAN`` grammar -> {point: _Clause}. ``spec`` may
    also be a dict of {point: clause-kwargs-dict | clause-string}."""
    clauses = {}
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = []
        for frag in str(spec).split(";"):
            frag = frag.strip()
            if not frag:
                continue
            point, _, rest = frag.partition(":")
            items.append((point.strip(), rest))
    for point, rest in items:
        if point not in FAULT_POINTS:
            raise MXNetError(
                f"unknown fault point {point!r} (known: "
                f"{', '.join(sorted(FAULT_POINTS))}; register custom "
                "points via register_fault_point)")
        if isinstance(rest, dict):
            kw = dict(rest)
        else:
            kw = {}
            for tok in str(rest).split(":"):
                tok = tok.strip()
                if not tok:
                    continue
                k, _, v = tok.partition("=")
                kw[k.strip()] = v.strip()
        exc = kw.pop("exc", InjectedFault)
        if isinstance(exc, str):
            if exc not in _EXC_BY_NAME:
                raise MXNetError(
                    f"unknown fault exception {exc!r} (known: "
                    f"{', '.join(sorted(_EXC_BY_NAME))})")
            exc = _EXC_BY_NAME[exc]
        clean = {}
        for k in ("at", "every", "after", "times"):
            if k in kw:
                clean[k] = int(kw.pop(k))
        if "prob" in kw:
            clean["prob"] = float(kw.pop("prob"))
        # per-CLAUSE seed: a clause-level seed= must not leak into the
        # clauses after it (order-dependent chaos plans are undebuggable)
        clause_seed = int(kw.pop("seed")) if "seed" in kw else seed
        if kw:
            raise MXNetError(
                f"unknown fault clause key(s) {sorted(kw)} for "
                f"{point!r} (known: at/every/prob/after/times/seed/exc)")
        clauses[point] = _Clause(point, exc=exc, seed=clause_seed,
                                 **clean)
    return clauses


def arm(spec, seed=None):
    """Arm a fault plan (replacing any active one). ``spec`` is the
    ``MXNET_FAULT_PLAN`` string or a {point: kwargs} dict."""
    global _PLAN
    plan = parse_plan(spec, seed=seed)
    with _LOCK:
        _PLAN = plan or None
    return plan


def disarm():
    """Drop the active plan (fault points go back to zero-cost)."""
    global _PLAN
    with _LOCK:
        _PLAN = None


def armed():
    # single global read; _PLAN swaps are atomic rebinds under _LOCK
    return _PLAN is not None  # graft-lint: allow(L1102)


class inject:
    """Context manager arming ONE point for the block::

        with faults.inject("kvstore_push", every=2, times=3):
            ...

    Restores the previously-armed plan (if any) on exit, so tests can
    nest scoped injections without trampling each other."""

    def __init__(self, point, **clause):
        self._spec = {point: clause}
        self._prev = None

    def __enter__(self):
        global _PLAN
        plan = parse_plan(self._spec)
        with _LOCK:
            self._prev = _PLAN
            _PLAN = plan
        return self

    def __exit__(self, *exc):
        global _PLAN
        with _LOCK:
            _PLAN = self._prev


def maybe_fail(point):
    """The seam hook: raise the armed exception when ``point``'s clause
    says this call fires, else return instantly. The disarmed cost is
    one global read — call it freely on hot paths."""
    # the disarmed fast path is ONE unlocked global read by design —
    # fault points sit on hot paths (every op push)
    plan = _PLAN  # graft-lint: allow(L1102)
    if plan is None:
        return
    clause = plan.get(point)
    if clause is None:
        return
    with _LOCK:
        fire = clause.should_fire()
        if fire:
            _FIRES[point] = _FIRES.get(point, 0) + 1
    if fire:
        from . import _count

        _count("fault_fires")
        raise clause.exc(
            f"injected fault at point {point!r} "
            f"(call {clause.calls}, fire {clause.fires})")


def fire_counts():
    """{point: total injected fires} since the last reset."""
    with _LOCK:
        return dict(_FIRES)


def reset_fire_counts():
    with _LOCK:
        _FIRES.clear()


def _init_from_env():
    """Arm the env-declared plan at first import (subprocess chaos
    drills set MXNET_FAULT_PLAN before launch; an empty/missing var is
    a no-op)."""
    from .. import env as _env

    spec = _env.get_str("MXNET_FAULT_PLAN")
    if spec:
        arm(spec)


_init_from_env()
