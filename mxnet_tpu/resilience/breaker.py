"""Circuit breaker: stop hammering a failing dependency.

The serving-side degradation primitive (the classic three-state
breaker every production fleet front-end carries): CLOSED passes calls
through and counts consecutive failures; ``threshold`` consecutive
failures TRIP it OPEN — calls fail fast with :class:`CircuitOpen`
(mapped to HTTP 503 by the model server) instead of queueing behind a
dependency that cannot serve them; after ``cooldown_ms`` the breaker
goes HALF-OPEN and admits one probe — a success closes it again, a
failure re-opens (and restarts the cooldown).

``mxnet_tpu/serving/session.py`` keeps one breaker per bucket
executable: a bucket that fails repeatedly is first DEMOTED from its
AOT/deserialized executable back to the plain jit path (a corrupt or
stale artifact must not poison the bucket forever), and only if the
jit path keeps failing does the breaker open. ``/healthz`` reflects
both states so a load balancer / operator sees the degradation.
"""
from __future__ import annotations

import time

from ..base import MXNetError
from ..utils import locks as _locks

__all__ = ["CircuitBreaker", "CircuitOpen"]


class CircuitOpen(MXNetError):
    """Fail-fast rejection: the breaker is open (HTTP 503 semantics —
    retry after the cooldown)."""


class CircuitBreaker:
    """Three-state (closed / open / half-open) breaker.

    Thread-safe: serving workers record outcomes concurrently. With
    the ``MXNET_RESILIENCE`` master switch off the breaker never
    trips (``allow`` is always True) — fail-fast policy belongs to
    the resilience layer, and disabling it must restore the previous
    always-try behavior.
    """

    def __init__(self, threshold=None, cooldown_ms=None, name="",
                 clock=None):
        from .. import env as _env

        self.threshold = int(
            threshold if threshold is not None else
            _env.get_int("MXNET_BREAKER_THRESHOLD", 5))
        self.cooldown_s = float(
            cooldown_ms if cooldown_ms is not None else
            _env.get_float("MXNET_BREAKER_COOLDOWN_MS", 30000.0)) / 1e3
        self.name = name
        self._clock = clock if clock is not None else time.monotonic
        # guards: _failures, _opened_at, _probing
        self._lock = _locks.RankedLock("resilience.breaker")
        self._failures = 0      # consecutive, while closed/half-open
        self._opened_at = None  # monotonic stamp, while open
        self._probing = False   # one half-open probe in flight

    @property
    def state(self):
        with self._lock:
            return self._state_locked()

    def _state_locked(self):
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.cooldown_s:
            return "half-open"
        return "open"

    @property
    def failures(self):
        with self._lock:
            return self._failures

    def allow(self):
        """True when a call may proceed (closed, or the single
        half-open probe). False = the caller must fail fast; the
        convenience :meth:`check` raises :class:`CircuitOpen` for it."""
        from . import _count, resilience_enabled

        if not resilience_enabled():
            return True
        with self._lock:
            st = self._state_locked()
            if st == "closed":
                return True
            if st == "half-open" and not self._probing:
                self._probing = True
                return True
        _count("breaker_fast_fails")
        return False

    def check(self):
        """``allow`` or raise :class:`CircuitOpen`."""
        if not self.allow():
            raise CircuitOpen(
                f"circuit {self.name or 'breaker'} is open after "
                f"{self.threshold} consecutive failure(s); retry after "
                f"the {self.cooldown_s * 1e3:.0f}ms cooldown")

    def record_success(self):
        from . import _count

        with self._lock:
            was_open = self._opened_at is not None
            self._failures = 0
            self._opened_at = None
            self._probing = False
        if was_open:
            _count("breaker_resets")

    def record_failure(self):
        from . import _count, resilience_enabled

        if not resilience_enabled():
            return
        with self._lock:
            self._failures += 1
            self._probing = False
            tripped = self._opened_at is None and \
                self._failures >= self.threshold
            if tripped or self._opened_at is not None:
                # trip, or re-open after a failed half-open probe:
                # either way the cooldown restarts now
                self._opened_at = self._clock()
        if tripped:
            _count("breaker_trips")
