"""Plan -> fused-step sharding config, with opt-in ZeRO-1 state sharding.

``fused_shard_cfg`` is the bridge the Gluon trainer crosses: given the
parameter-group names/shapes and optimizer-state signatures, it turns
the scoped :class:`ShardingPlan` into the concrete ``NamedSharding``
trees the fused executable is compiled with (``in_shardings`` /
``out_shardings``) and the trainer places buffers with.

Optimizer-state layout:

- default: a state leaf with the parameter's shape follows the
  parameter's spec (momentum/variance co-located with the weight);
  other leaves (scalars, fp16 base copies of different shape)
  replicate;
- ZeRO-1 (``MXNET_SHARDING_ZERO1=1``): additionally shards every
  param-shaped state leaf's dim 0 over the mesh's FIRST axis — the
  cross-replica weight-update sharding of "Automatic Cross-Replica
  Sharding of Weight Update in Data-Parallel Training". Each device
  then stores 1/N of the optimizer state and computes 1/N of the
  update; GSPMD inserts the all-gather that re-materializes the
  updated weights at the parameters' plan layout. Dims the axis
  doesn't divide fall back to the default layout (counted as
  ``divisibility_fallbacks``).
"""
from __future__ import annotations

from jax.sharding import NamedSharding

from . import _count, zero1_enabled
from .plan import _to_pspec, current_plan

__all__ = ["fused_shard_cfg", "FusedShardCfg"]


class FusedShardCfg:
    """Resolved sharding for one fused-step parameter group."""

    __slots__ = ("mesh", "param_shardings", "state_shardings", "rep",
                 "salt", "zero1")

    def __init__(self, mesh, param_shardings, state_shardings, rep,
                 salt, zero1):
        self.mesh = mesh
        self.param_shardings = param_shardings
        self.state_shardings = state_shardings
        self.rep = rep
        self.salt = salt
        self.zero1 = zero1

    def place_args(self, pvals, gvals, svals, donate_params):
        """Move the step's input buffers to the declared layouts.

        jit with explicit ``in_shardings`` REJECTS a committed arg at a
        different layout (it only auto-reshards uncommitted arrays), so
        the first sharded step — and the first one after a checkpoint
        restore re-binds single-device buffers — must place inputs
        itself. Already-placed buffers pass through by identity, so the
        steady-state cost is one sharding comparison per buffer.

        Buffers the executable DONATES (states always; params under
        ``donate_params``) are additionally laundered through a
        device-side copy: donating a raw transfer's buffer is unsafe on
        jaxlib 0.4.37's CPU client (the round-12 corruption bug), while
        a computation output donates safely everywhere."""
        import jax
        import jax.numpy as jnp

        def leaf(x, sh, launder):
            if x is None or sh is None:
                return x
            if getattr(x, "sharding", None) == sh:
                return x
            x = jax.device_put(x, sh)
            return jnp.array(x, copy=True) if launder else x

        def tree(x, sh, launder):
            if isinstance(sh, tuple):
                return tuple(tree(a, b, launder) for a, b in zip(x, sh))
            return leaf(x, sh, launder)

        pvals = tuple(leaf(p, sh, donate_params)
                      for p, sh in zip(pvals, self.param_shardings))
        gvals = tuple(leaf(g, sh, False)
                      for g, sh in zip(gvals, self.param_shardings))
        svals = tuple(tree(s, sh, True)
                      for s, sh in zip(svals, self.state_shardings))
        return pvals, gvals, svals


def _zero1_entries(pentries, shape, axis, axis_sizes):
    """Prepend the ZeRO-1 axis to dim 0 of a param-shaped state spec;
    None when the combined extent doesn't divide dim 0."""
    if not shape:
        return None
    entries = list(pentries) + [None] * (len(shape) - len(pentries))
    head_axes = entries[0] or ()
    if axis in head_axes:
        return None  # dim 0 already sharded over this axis by the plan
    # existing extent on dim 0 multiplies in — the combined split must
    # still divide
    extent = axis_sizes[axis]
    for a in head_axes:
        extent *= axis_sizes[a]
    return None if extent <= 0 or shape[0] % extent != 0 else \
        tuple([(axis,) + tuple(head_axes)] + entries[1:])


def _state_shardings(sig, pspec, pshape, mesh, zero1_axis):
    """state_sig tree -> matching tree of NamedSharding/None leaves.
    Returns (tree, used_zero1)."""
    if sig is None:
        return None, False
    is_leaf = (len(sig) == 2 and isinstance(sig[0], tuple)
               and isinstance(sig[1], str))
    if not is_leaf:  # nested tuple of sub-state sigs
        parts = [_state_shardings(s, pspec, pshape, mesh, zero1_axis)
                 for s in sig]
        return tuple(p[0] for p in parts), any(p[1] for p in parts)
    shape, _dtype = sig
    shape = tuple(shape)
    axis_sizes = dict(mesh.shape)
    if shape != tuple(pshape) or not shape or all(d <= 1 for d in shape):
        return NamedSharding(mesh, _to_pspec(())), False
    pentries = [None if e is None else
                (tuple(e) if isinstance(e, (tuple, list)) else (e,))
                for e in tuple(pspec)]
    if zero1_axis is not None:
        z = _zero1_entries(pentries, shape, zero1_axis, axis_sizes)
        if z is not None:
            return NamedSharding(mesh, _to_pspec(z)), True
        _count("divisibility_fallbacks")
    return NamedSharding(mesh, _to_pspec(pentries)), False


def fused_shard_cfg(named_shapes, state_sigs):
    """The :class:`FusedShardCfg` for the scoped plan, or None when no
    plan is active. ``named_shapes``: ordered (name, shape) pairs for
    the group's params; ``state_sigs``: the matching
    ``fused_step.state_sig`` trees."""
    ctx = current_plan()
    if ctx is None:
        return None
    plan, mesh = ctx
    zero1 = zero1_enabled()
    zero1_axis = next(iter(dict(mesh.shape))) if zero1 else None
    pshards, sshards = [], []
    any_zero1 = False
    for (name, shape), sig in zip(named_shapes, state_sigs):
        spec = plan.spec_for(name, shape, mesh)
        pshards.append(NamedSharding(mesh, spec))
        tree, used = _state_shardings(sig, spec, shape, mesh, zero1_axis)
        sshards.append(tree)
        any_zero1 = any_zero1 or used
    rep = NamedSharding(mesh, _to_pspec(()))
    # deliberate legacy site: this salt rides the fused-step cache KEY
    # (FusedShardCfg travels through the trainer into cache_key), not
    # a CompiledArtifact salts=() declaration — the "sharding" provider
    # covers the serving path only
    salt = plan.fingerprint_salt(mesh) + (  # graft-lint: allow(L1001)
        "zero1", zero1)
    _count("fused_sharded_groups")
    if any_zero1:
        _count("zero1_groups")
    return FusedShardCfg(mesh, tuple(pshards), tuple(sshards), rep,
                         salt, any_zero1)
