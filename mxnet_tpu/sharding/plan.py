"""ShardingPlan: regex partition rules -> PartitionSpecs vs a mesh.

The rule shape follows the proven ``match_partition_rules`` idiom:
ordered ``(regex, spec)`` pairs, first ``re.search`` hit wins, scalars
and size-1 leaves replicate unconditionally. On top of that the plan
adds what a production trainer needs:

- a **divisibility fallback**: a matched dim whose size the mesh extent
  doesn't divide (or a spec entry naming an axis the mesh lacks) falls
  back to replicating THAT dim instead of erroring mid-train — each
  fallback ticks ``sharding_counters()['divisibility_fallbacks']`` and
  ``analysis.verify_plan`` reports the static mismatch;
- an ``unmatched='replicate' | 'error'`` policy for names no rule
  covers;
- a process-stable ``fingerprint_salt`` so compile caches (fused step,
  serving AOT) key sharded executables separately per plan;
- a scope stack (``plan_scope`` / ``current_plan``) mirroring
  ``parallel.mesh.mesh_scope`` that consumers read.
"""
from __future__ import annotations

import re

from jax.sharding import NamedSharding, PartitionSpec

from . import _count

__all__ = ["ShardingPlan", "plan_scope", "current_plan", "replicated",
           "named_sharding", "plan_from_env"]


def _normalize_entry(entry):
    """One PartitionSpec position -> None | (axis names...)."""
    if entry is None:
        return None
    if isinstance(entry, (tuple, list)):
        return tuple(str(a) for a in entry)
    return (str(entry),)


def _normalize_spec(spec):
    """PartitionSpec | iterable of entries -> tuple of normalized
    entries (the plan's canonical, hashable spec form)."""
    if isinstance(spec, PartitionSpec):
        spec = tuple(spec)
    elif spec is None:
        spec = ()
    elif isinstance(spec, str):
        spec = (spec,)
    return tuple(_normalize_entry(e) for e in tuple(spec))


def _to_pspec(entries):
    return PartitionSpec(*[None if e is None else
                           (e[0] if len(e) == 1 else e)
                           for e in entries])


def replicated(mesh):
    """The fully-replicated NamedSharding on ``mesh`` — the blessed
    constructor consumers outside ``sharding/``/``parallel/`` use
    instead of raw ``NamedSharding(mesh, PartitionSpec())`` (graft_lint
    L701)."""
    return NamedSharding(mesh, PartitionSpec())


def named_sharding(mesh, spec):
    """NamedSharding from a plan-canonical entry tuple, a PartitionSpec,
    or anything ``_normalize_spec`` accepts."""
    return NamedSharding(mesh, _to_pspec(_normalize_spec(spec)))


class ShardingPlan:
    """Ordered ``(regex, spec)`` partition rules over named arrays.

    ``spec`` per rule is a ``PartitionSpec``, or a tuple of per-dim
    entries (``None`` | axis name | tuple of axis names). ``unmatched``
    decides names no rule covers: ``'replicate'`` (default) or
    ``'error'``. ``fallback=False`` disables the per-dim divisibility
    fallback and the scalar shortcut — specs then apply verbatim (the
    legacy ``parallel.spmd.shard_params`` contract, where validation is
    the caller's job).
    """

    def __init__(self, rules, unmatched="replicate", fallback=True):
        if unmatched not in ("replicate", "error"):
            raise ValueError(
                f"unmatched must be 'replicate' or 'error', got "
                f"{unmatched!r}")
        if hasattr(rules, "items"):
            rules = list(rules.items())
        self._rules = tuple(
            (str(pat), re.compile(str(pat)), _normalize_spec(spec))
            for pat, spec in rules)
        self.unmatched = unmatched
        self.fallback = bool(fallback)
        self._salts = {}
        _count("plans_built")

    @property
    def rules(self):
        """Canonical ``(pattern, spec entries)`` pairs, in match order."""
        return tuple((pat, spec) for pat, _, spec in self._rules)

    def match(self, name):
        """The first rule matching ``name`` -> (pattern, spec entries),
        or None."""
        for pat, rx, spec in self._rules:
            if rx.search(name):
                return pat, spec
        return None

    def _raw_spec(self, name, shape):
        """Pre-fallback resolution: the matched rule's entries padded /
        truncated to the array's rank, or the unmatched policy."""
        hit = self.match(name)
        if hit is None:
            if self.unmatched == "error":
                raise ValueError(
                    f"no sharding rule matches '{name}' and the plan's "
                    f"unmatched policy is 'error' (patterns: "
                    f"{[p for p, _ in self.rules]})")
            _count("rules_unmatched")
            return ()
        _count("rules_matched")
        return hit[1]

    def spec_for(self, name, shape, mesh):
        """The PartitionSpec for one named array, divisibility fallback
        applied (unless ``fallback=False``)."""
        shape = tuple(shape)
        raw = self._raw_spec(name, shape)
        if not self.fallback:
            return _to_pspec(raw)
        if len(shape) == 0 or all(d <= 1 for d in shape):
            return PartitionSpec()  # scalars / size-1 leaves replicate
        axis_sizes = dict(mesh.shape)
        entries = []
        for dim, axes in enumerate(raw):
            if dim >= len(shape):
                break  # spec longer than rank: extra entries dropped
            if axes is None:
                entries.append(None)
                continue
            extent = 1
            known = all(a in axis_sizes for a in axes)
            if known:
                for a in axes:
                    extent *= axis_sizes[a]
            if not known or extent <= 0 or shape[dim] % extent != 0:
                _count("divisibility_fallbacks")
                entries.append(None)  # replicate just this dim
                continue
            entries.append(axes)
        return _to_pspec(entries)

    def specs(self, named_shapes, mesh):
        """{name: PartitionSpec} for a {name: shape} tree."""
        return {name: self.spec_for(name, shape, mesh)
                for name, shape in named_shapes.items()}

    def shardings(self, named_shapes, mesh=None):
        """{name: NamedSharding} resolved against ``mesh`` (default:
        the scoped/current mesh). Final specs are re-checked through
        ``analysis.verify_shardings`` under MXNET_GRAPH_VERIFY — with
        the fallback on they are clean by construction, so this is the
        safety net for ``fallback=False`` plans."""
        from ..parallel.mesh import current_mesh

        mesh = mesh if mesh is not None else current_mesh()
        if mesh is None:
            raise ValueError(
                "ShardingPlan.shardings needs a mesh (pass one, or "
                "enter parallel.mesh_scope / sharding.plan_scope)")
        specs = self.specs(named_shapes, mesh)
        from ..analysis import verify_mode, verify_shardings

        if verify_mode() != "off":
            verify_shardings(
                {n: tuple(s) for n, s in named_shapes.items()},
                specs, mesh=mesh,
                subject="sharding plan").disposition()
        return {name: NamedSharding(mesh, spec)
                for name, spec in specs.items()}

    def fingerprint_salt(self, mesh=None):
        """Process-stable tuple identifying (plan, mesh layout) for
        compile-cache keys — the serving fingerprint and the fused-step
        LRU key both append this so plan or mesh-shape changes miss
        instead of serving a stale layout."""
        mesh_key = None
        if mesh is not None:
            mesh_key = tuple(
                (str(a), int(s)) for a, s in dict(mesh.shape).items())
        cached = self._salts.get(mesh_key)
        if cached is None:
            cached = ("sharding_plan", self.rules, self.unmatched,
                      self.fallback, mesh_key)
            self._salts[mesh_key] = cached
        return cached


# -- rules grammar (MXNET_SHARDING_RULES) -----------------------------------
#
#   rule  ; rule ; ...          rules are ';'-separated, matched in order
#   rule  := pattern = entries  pattern is a Python regex (no '=' or ';')
#   entries := entry , entry    one entry per array dim, ',' separated
#   entry := *                  replicate this dim
#          | axis               shard over one mesh axis
#          | axis+axis          shard over multiple axes (row-major)
#
# e.g. MXNET_SHARDING_RULES='.*dense.*weight=mp,*; .*=*'

def parse_rules(text):
    """The MXNET_SHARDING_RULES grammar -> canonical rule pairs."""
    rules = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(
                f"bad sharding rule {clause!r}: expected "
                "'pattern=entry,entry,...'")
        pat, _, entries = clause.partition("=")
        spec = []
        for entry in entries.split(","):
            entry = entry.strip()
            if entry in ("*", ""):
                spec.append(None)
            elif "+" in entry:
                spec.append(tuple(a.strip() for a in entry.split("+")))
            else:
                spec.append(entry)
        rules.append((pat.strip(), tuple(spec)))
    return rules


def plan_from_env():
    """The plan MXNET_SHARDING_RULES declares (None when unset/empty);
    MXNET_SHARDING_UNMATCHED picks the unmatched policy."""
    from .. import env as _env

    text = _env.get_str("MXNET_SHARDING_RULES", "")
    if not text.strip():
        return None
    return ShardingPlan(
        parse_rules(text),
        unmatched=_env.get_str("MXNET_SHARDING_UNMATCHED", "replicate"))


# -- scope ------------------------------------------------------------------

_CURRENT = []


class plan_scope:
    """Install (plan, mesh) as the active sharding declaration; the
    fused step, serving and the CheckpointManager read it via
    ``current_plan``. Mirrors ``parallel.mesh.mesh_scope`` (and nests
    the same way); does NOT enter a mesh_scope itself — the plan's mesh
    binding is explicit."""

    def __init__(self, plan, mesh=None):
        from ..parallel.mesh import current_mesh

        if mesh is None:
            mesh = current_mesh()
        if mesh is None:
            raise ValueError("plan_scope needs a mesh (pass one or "
                             "enter parallel.mesh_scope first)")
        self._pair = (plan, mesh)

    def __enter__(self):
        _CURRENT.append(self._pair)
        return self._pair

    def __exit__(self, *exc):
        _CURRENT.pop()


def current_plan():
    """The innermost active (plan, mesh) pair, or None. Inert (None)
    while MXNET_SHARDING=0 so one knob disables every consumer."""
    from . import sharding_enabled

    if not _CURRENT or not sharding_enabled():
        return None
    return _CURRENT[-1]


def place_params(params, plan=None, mesh=None):
    """Move initialized parameter buffers (and their grads) to the
    plan's layouts — the entry ritual of a plan scope.

    Eager JAX refuses to mix arrays committed to different device sets,
    so once anything rides the mesh *everything* in the model must:
    call this right after entering ``plan_scope`` (params still on one
    device) and place each batch with ``parallel.replicate`` /
    ``parallel.shard_batch``. Buffers already at their declared layout
    pass through untouched, so calling it again (e.g. after a
    checkpoint restore re-binds single-device buffers) is cheap.

    ``params`` is a ParameterDict or iterable of (name, Parameter);
    uninitialized (deferred) parameters are skipped — run one forward
    first or pass explicit in-shapes. Defaults to the scoped plan/mesh.
    """
    import jax

    if plan is None or mesh is None:
        ctx = current_plan()
        if ctx is None:
            raise ValueError("place_params needs a plan: pass one or "
                             "call inside sharding.plan_scope")
        plan = plan if plan is not None else ctx[0]
        mesh = mesh if mesh is not None else ctx[1]
    items = params.items() if hasattr(params, "items") else params
    for name, p in items:
        nd_obj = getattr(p, "_ndarray", None)
        if nd_obj is None:
            continue  # deferred init: first forward will create it
        sh = named_sharding(
            mesh, plan.spec_for(name, tuple(nd_obj.shape), mesh))
        if getattr(nd_obj._data, "sharding", None) != sh:
            nd_obj._data = jax.device_put(nd_obj._data, sh)
        g = getattr(nd_obj, "_grad", None)
        if g is not None and getattr(g._data, "sharding", None) != sh:
            g._data = jax.device_put(g._data, sh)


# -- artifact-layer salt provider -------------------------------------------
# ctx["shard"] is the serving-session shard declaration ({"plan", "mesh"}
# once shard_params ran, else None/absent)

def _salt_provider(ctx):
    shard = ctx.get("shard")
    if not shard:
        return ("sharding", 0)
    return shard["plan"].fingerprint_salt(shard["mesh"])


from ..artifact import salts as _artifact_salts  # noqa: E402

_artifact_salts.register_salt_provider("sharding", _salt_provider)
