"""Rule-based SPMD sharding: declare a partition plan ONCE, every
subsystem honors it.

The reference framework spreads placement decisions across kvstore
types, ``__ctx_group__`` attributes and executor group construction;
the TPU build replaces all of that with ONE declarative object — a
:class:`ShardingPlan` of ``(regex, PartitionSpec)`` rules matched
against parameter names (the ``match_partition_rules`` idiom from the
EasyLM/t5x lineage) and resolved against a ``parallel.mesh`` Mesh.
A plan installed with :func:`plan_scope` flows into:

- the fused train step (``gluon/fused_step.py``): parameter, gradient
  and optimizer-state buffers are laid out per plan and the ONE
  donated executable is compiled with matching in/out shardings —
  with opt-in ZeRO-1 cross-replica weight-update sharding
  (``MXNET_SHARDING_ZERO1``, after "Automatic Cross-Replica Sharding
  of Weight Update in Data-Parallel Training": optimizer state lives
  1/N-per-device and GSPMD inserts the update-side collectives);
- serving (``serving/session.py``): ``InferenceSession.shard_params``
  places the parameter snapshot per plan for tensor-parallel
  inference, and the AOT fingerprint is salted with the plan so
  sharded and unsharded executables never collide;
- checkpoints (``resilience/checkpoint.py``): mesh-sharded buffers
  are saved per-shard with a sharding manifest and reassembled on
  restore — onto a DIFFERENT mesh shape if the restoring process has
  one (resharding-on-load).

``parallel.spmd.shard_params`` is a thin shim over the same matcher.
Plan-vs-mesh static validation lives in ``analysis.sharding``
(``verify_plan``); counters surface via ``profiler.sharding_counters``
and the ``SHARDING`` runtime feature flag.
"""
from __future__ import annotations

from ..telemetry import metrics as _telemetry

__all__ = ["ShardingPlan", "plan_scope", "current_plan", "sharding_enabled",
           "zero1_enabled", "sharding_counters", "reset_sharding_counters",
           "replicated", "named_sharding", "plan_from_env",
           "place_params", "fused_shard_cfg"]


def sharding_enabled():
    """MXNET_SHARDING knob (default on); 0 disables every plan-driven
    path (plan scopes become inert). Read per use so tests can toggle
    without reimport."""
    from .. import env as _env

    return _env.get_bool("MXNET_SHARDING", True)


def zero1_enabled():
    """MXNET_SHARDING_ZERO1 — OPT-IN (default 0) ZeRO-1 cross-replica
    weight-update sharding: optimizer state shards its leading dim over
    the mesh (1/N bytes per device) and GSPMD all-gathers the updated
    weights, instead of every device carrying and updating a full
    replica."""
    from .. import env as _env

    return _env.get_bool("MXNET_SHARDING_ZERO1", False)


def _zero_counters():
    return {"plans_built": 0, "rules_matched": 0, "rules_unmatched": 0,
            "divisibility_fallbacks": 0, "fused_sharded_groups": 0,
            "zero1_groups": 0, "serving_sharded_sessions": 0,
            "ckpt_shard_files": 0, "ckpt_sharded_saves": 0,
            "ckpt_sharded_restores": 0, "ckpt_reshards": 0}


# registry-owned since round 18 (unified Prometheus/trace surface)
_COUNTERS = _telemetry.counter_family("sharding", _zero_counters())


def _count(name, delta=1):
    _COUNTERS.add(name, delta)


def sharding_counters():
    """Plan/consumer counters (zeros before first use):
    rule matching (``rules_matched``/``rules_unmatched``/
    ``divisibility_fallbacks``), fused-step groups compiled under a plan
    (``fused_sharded_groups``/``zero1_groups``), serving sessions with
    sharded snapshots, and sharded-checkpoint traffic
    (``ckpt_shard_files``/``ckpt_reshards``/...)."""
    out = _COUNTERS.snapshot()
    out["enabled"] = sharding_enabled()
    return out


def reset_sharding_counters():
    _COUNTERS.reset()


from .plan import (ShardingPlan, plan_scope, current_plan,  # noqa: E402
                   replicated, named_sharding, place_params, plan_from_env)
from .zero1 import fused_shard_cfg  # noqa: E402
