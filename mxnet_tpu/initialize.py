"""Process initialization: crash tracebacks and fork safety.

Reference: src/initialize.cc — the reference installs a SIGSEGV handler
that prints a C++ stack trace (MXNET_USE_SIGNAL_HANDLER) and pthread
atfork hooks that stop the engine's worker threads before fork and
restart them in parent and child (LibraryInitializer::install_pthread_
atfork_handlers; threads never survive fork, so a child inheriting a
"running" engine would deadlock on its first push).

TPU-native equivalents:
- crash tracebacks via ``faulthandler`` (SIGSEGV/SIGFPE/SIGABRT/SIGBUS
  dump the Python stack of every thread — the useful trace here, since
  compute crashes surface through the XLA runtime's own diagnostics);
- ``os.register_at_fork`` resets the engine singleton and the pooled
  storage handle in the child, so a forked worker lazily builds fresh
  worker threads instead of deadlocking on the parent's dead ones.

Both install at import (mxnet_tpu/__init__) and honor the reference's
MXNET_USE_SIGNAL_HANDLER knob (default on, like the reference wheels).
"""
from __future__ import annotations

import faulthandler
import io
import os
import sys

from . import env as _env

_installed = {"signals": False, "fork": False}


def install_signal_handlers():
    """Enable crash tracebacks (reference: initialize.cc SegfaultLogger)."""
    if _installed["signals"]:
        return
    if not _env.get_bool("MXNET_USE_SIGNAL_HANDLER", True):
        return
    try:
        faulthandler.enable(file=sys.stderr, all_threads=True)
        _installed["signals"] = True
    except (RuntimeError, io.UnsupportedOperation, AttributeError):
        pass  # no usable stderr (embedded interpreter)


def _reinit_child():
    """After fork, the child owns no engine/kvstore worker threads —
    drop the singletons so they rebuild lazily (reference:
    LibraryInitializer::atfork_child resets the engine)."""
    from . import engine as _engine
    from . import storage as _storage

    # LOCKLESS on purpose: the child is single-threaded right after
    # fork, and _engine_lock may have been COW-copied in the locked
    # state if another parent thread was inside engine.get() — taking
    # it here would deadlock the fork (threading.Lock is not
    # fork-safe). Plain assignment is atomic enough for one thread;
    # the lock itself is replaced too, else the child's first
    # engine.get() would block on the orphaned held lock.
    from .utils import locks as _locks

    _engine._engine = None
    _engine._engine_lock = _locks.RankedLock("engine.singleton")
    # the native pool's mutex/freelist were COW-snapshotted mid-flight;
    # the child must not touch the parent's pool
    _storage._storage = None


def install_fork_handlers():
    if _installed["fork"]:
        return
    if hasattr(os, "register_at_fork"):
        os.register_at_fork(after_in_child=_reinit_child)
        _installed["fork"] = True


def initialize():
    install_signal_handlers()
    install_fork_handlers()
