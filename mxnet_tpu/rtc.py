"""User-defined kernels at runtime (reference: python/mxnet/rtc.py
CudaModule over src/common/rtc.cc NVRTC compilation).

On TPU the runtime-kernel mechanism is Pallas: PallasModule wraps
user-written kernel functions and `launch` maps them over a grid via
pl.pallas_call — same role as CudaModule.get_kernel().launch(), with the
Mosaic compiler standing in for NVRTC.
"""
from __future__ import annotations

__all__ = ["PallasModule", "CudaModule"]


class _Kernel:
    def __init__(self, fn, name):
        self._fn = fn
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               out_shape=None, out_dtype="float32", shared_mem=0,
               interpret=None):
        """Run the kernel. args: NDArrays/jax arrays; out_shape defaults
        to the first input's shape. grid_dims maps to the pallas grid
        (block_dims/shared_mem accepted for CudaModule API parity — VMEM
        blocking is expressed in the kernel's BlockSpecs instead)."""
        import jax
        import jax.numpy as jnp
        # the RTC surface exists to run USER-written Pallas kernels —
        # deliberately outside the kernels-package fusion discipline
        from jax.experimental import pallas as pl  # graft-lint: allow(L801)

        from .ndarray import NDArray

        datas = [a.data if isinstance(a, NDArray) else jnp.asarray(a)
                 for a in args]
        if out_shape is None:
            out_shape = datas[0].shape
            out_dtype = datas[0].dtype
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        kw = {}
        if grid_dims:
            kw["grid"] = tuple(grid_dims)
        call = pl.pallas_call(
            self._fn,
            out_shape=jax.ShapeDtypeStruct(tuple(out_shape),
                                           jnp.dtype(out_dtype)),
            interpret=interpret, **kw)
        out = call(*datas)
        return NDArray(out)


class PallasModule:
    """Collection of named Pallas kernels (reference shape: CudaModule
    holding NVRTC-compiled kernels, rtc.py:CudaModule)."""

    def __init__(self, kernels=None, **named):
        self._kernels = {}
        if kernels:
            self._kernels.update(kernels)
        self._kernels.update(named)

    def add_kernel(self, name, fn):
        self._kernels[name] = fn
        return self

    def get_kernel(self, name, signature=None):
        """signature accepted for CudaModule API parity (typing is carried
        by the jax arrays themselves)."""
        if name not in self._kernels:
            raise ValueError(f"kernel '{name}' not in module "
                             f"(has {sorted(self._kernels)})")
        return _Kernel(self._kernels[name], name)


def CudaModule(*args, **kwargs):
    """The reference's NVRTC entry point has no TPU meaning — direct users
    to PallasModule (reference: rtc.py:CudaModule)."""
    raise NotImplementedError(
        "CUDA RTC is not available on TPU; write a Pallas kernel and wrap "
        "it with mxnet_tpu.rtc.PallasModule instead")
