"""Monitor: per-layer tensor statistics during training.

Reference: python/mxnet/monitor.py — taps every op output via executor
monitor callbacks (graph_executor.cc:1343-1382). Here the tap points are
Gluon Block forwards (installed with Monitor.install(block)) and Module
executor outputs; stat_func runs on-device and syncs only at toc().
"""
from __future__ import annotations

import re

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                from . import nd

                return nd.norm(x) / (x.size ** 0.5)
        self.interval = interval
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._hooks = []

    def install(self, block_or_exe):
        """Attach to a Gluon Block tree (wraps each child's forward) or an
        Executor (reads outputs at toc)."""
        from .gluon.block import Block

        if isinstance(block_or_exe, Block):
            self._install_block(block_or_exe, prefix="")
        else:
            exe = block_or_exe
            self._hooks.append(("exe", exe))
        return block_or_exe

    def _install_block(self, block, prefix):
        for name, child in block._children.items():
            cname = getattr(child, "name", None) or name
            self._install_block(child, prefix + cname + ".")
        orig = block.forward
        mon = self

        def wrapped(*args, _orig=orig, _name=prefix.rstrip("."), **kw):
            out = _orig(*args, **kw)
            if mon.activated and _name and mon.re_pattern.match(_name):
                outs = out if isinstance(out, (list, tuple)) else [out]
                for i, o in enumerate(outs):
                    if hasattr(o, "data"):
                        mon.queue.append((mon.step, f"{_name}_output{i}",
                                          mon.stat_func(o)))
            return out

        block.forward = wrapped
        self._hooks.append(("block", block, orig))

    def install_to_executor(self, exe, monitor_all=False):
        """Attach to an Executor's per-op-output taps (reference:
        monitor.py install → executor set_monitor_callback)."""
        mon = self

        def cb(name, arr):
            if mon.activated and mon.re_pattern.match(name):
                mon.queue.append((mon.step, name, mon.stat_func(arr)))

        # lets the executor skip the tap computation on steps where the
        # interval gate is closed (no tic since the last toc)
        cb.mx_monitor_active = lambda: mon.activated
        exe.set_monitor_callback(cb, monitor_all=monitor_all)
        self._hooks.append(("exe_cb", exe))
        return exe

    def uninstall(self):
        for h in self._hooks:
            if h[0] == "block":
                h[1].forward = h[2]
            elif h[0] == "exe_cb":
                h[1].set_monitor_callback(None)
        self._hooks = []

    def tic(self):
        """Start collecting for this step (every `interval` steps)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Finish the step; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        # executor taps: read outputs + aux now
        for h in self._hooks:
            if h[0] == "exe":
                exe = h[1]
                for i, o in enumerate(getattr(exe, "outputs", [])):
                    self.queue.append((self.step, f"output{i}",
                                       self.stat_func(o)))
        res = []
        queue = sorted(self.queue, key=lambda q: q[1]) if self.sort \
            else self.queue
        for n, name, stat in queue:
            res.append((n, name, str(stat.asnumpy().reshape(-1)[:4])
                        if hasattr(stat, "asnumpy") else str(stat)))
        self.queue = []
        return res

    def toc_print(self):
        for n, name, stat in self.toc():
            print(f"Batch: {n:7d} {name:30s} {stat}")
