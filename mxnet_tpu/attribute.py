"""Attribute scoping for symbol composition (reference:
python/mxnet/attribute.py).

``with AttrScope(ctx_group='dev1'):`` stamps every symbol created inside
the block with the scope's attributes (merged over enclosing scopes,
inner wins). The reference uses this for ctx_group placement,
``__wd_mult__``/``__lr_mult__`` per-layer hyperparameters, and mirroring
hints — all of which ride on symbol attrs in the exported JSON.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_scope = threading.local()


def current():
    """The innermost active scope (an empty root if none entered)."""
    stack = getattr(_scope, "stack", None)
    if not stack:
        _scope.stack = stack = [AttrScope()]
    return stack[-1]


class AttrScope:
    """A dict of symbol attributes applied to nodes created in-scope
    (reference: attribute.py AttrScope)."""

    def __init__(self, **attrs):
        for k, v in attrs.items():
            if not isinstance(v, str):
                raise ValueError(
                    f"AttrScope values must be strings; got {k}={v!r}")
        self._attrs = attrs
        self._merged = None  # set on __enter__: parent attrs + own

    def get(self, attrs=None):
        """Scope attributes merged with explicit `attrs` (explicit wins,
        matching the reference's update order)."""
        base = dict(self._merged if self._merged is not None
                    else self._attrs)
        if attrs:
            base.update(attrs)
        return base

    def __enter__(self):
        if not getattr(_scope, "stack", None):
            _scope.stack = [AttrScope()]
        parent = _scope.stack[-1]
        self._merged = parent.get(self._attrs)
        _scope.stack.append(self)
        return self

    def __exit__(self, *exc):
        _scope.stack.pop()
        self._merged = None
