"""Bucketed dispatch-as-ready gradient all-reduce.

The round-7 distributed path coalesces all dense grads into ONE
collective per dtype — but that collective only dispatches inside
``Trainer.step``, AFTER the whole backward finished: communication and
backward compute fully serialize. This module overlaps them (horovod /
DDP-style gradient bucketing; the schedulable-weight-update framing of
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training"):

- ``AsyncGradReducer.attach()`` registers an autograd **grad-ready
  hook**: ``autograd.backward`` signals each marked variable the moment
  its gradient is written;
- ready grads accumulate into per-dtype buckets; when a bucket reaches
  ``MXNET_GRAD_BUCKET_KB`` bytes its all-reduce dispatches IMMEDIATELY
  (XLA dispatch is async — the collective rides the device while the
  host continues the remaining backward);
- dispatched reductions are **speculative**: the reducer records the
  exact input buffer it reduced, and ``flush()`` (called from
  ``Trainer.allreduce_grads`` at step time) binds a speculative result
  only if the grad buffer is still the one it reduced. A grad
  overwritten or accumulated into after dispatch (double backward,
  ``grad_req='add'`` accumulation) discards the stale speculation and
  re-reduces the current value — correctness never depends on "one
  backward per step".

Bucketing is bitwise-neutral: the reduction is elementwise, so
``concat(psum) == psum(concat)`` whatever the bucket boundaries
(``parallel.all_reduce_coalesced``'s contract). Every worker runs the
same program in the same order, so bucket fill — and therefore the
collective sequence — is identical across workers.

Single process, ``all_reduce`` is the identity and the reducer is pure
bookkeeping; pass ``reduce_fn`` to observe/override the per-bucket
collective (tests, custom comm backends, gradient compression via
``GradientCompression`` wire formats).
"""
from __future__ import annotations

from ..telemetry import tracer as _telem
from . import (_count, async_grad_sync_enabled, grad_bucket_bytes)

__all__ = ["AsyncGradReducer"]


class AsyncGradReducer:
    """Dispatch-as-ready bucketed all-reduce over a parameter group.

    Single-threaded by design: the autograd hook fires on the thread
    running ``backward`` and ``flush()`` on the thread running
    ``step()`` — the training loop's thread in both cases.
    """

    def __init__(self, params, bucket_bytes=None, reduce_fn=None):
        self._params = list(params)
        self._bucket_bytes = bucket_bytes
        self._reduce_fn = reduce_fn
        self._by_id = {}        # id(param._ndarray) -> Parameter
        self._unhook = None
        self._pending = {}      # dtype str -> [(grad NDArray, captured jnp)]
        self._pending_bytes = {}
        self._spec = {}         # id(grad NDArray) -> (captured, reduced)
        self._round_enabled = None  # knob, read once per round

    # -- wiring -------------------------------------------------------------

    def attach(self):
        """Register the grad-ready hook (idempotent). The hook stays
        registered across steps; ``MXNET_ASYNC_GRAD_SYNC=0`` turns each
        round into a no-op so toggling needs no re-wiring. The global
        hook holds this reducer (and its parameter group) only weakly —
        a dropped trainer unregisters itself on the next backward."""
        if self._unhook is None:
            import weakref

            from .. import autograd

            self._refresh_index()
            ref = weakref.ref(self)
            handle = []

            def hook(arr):
                r = ref()
                if r is None:
                    handle[0]()
                else:
                    r._on_grad_ready(arr)

            handle.append(autograd.register_grad_ready_hook(hook))
            self._unhook = handle[0]
        return self

    def detach(self):
        if self._unhook is not None:
            self._unhook()
            self._unhook = None

    def _refresh_index(self):
        self._by_id = {
            id(p._ndarray): p for p in self._params
            if getattr(p, "_ndarray", None) is not None
            and p.grad_req != "null"}

    # -- dispatch-as-ready --------------------------------------------------

    def _on_grad_ready(self, arr):
        """Called by ``autograd.backward`` right after ``arr._grad`` is
        written. Cheap rejects first — the hook runs once per marked
        variable per backward."""
        if self._round_enabled is None:
            self._round_enabled = async_grad_sync_enabled()
            if self._round_enabled:
                self._refresh_index()  # params may have (re)materialized
        if not self._round_enabled:
            return
        p = self._by_id.get(id(arr))
        if p is None:
            return
        g = arr._grad
        if g is None or not self._reducible(g):
            return
        data = g._data
        key = str(data.dtype)
        self._pending.setdefault(key, []).append((g, data))
        size = self._pending_bytes.get(key, 0) + data.size * data.dtype.itemsize
        self._pending_bytes[key] = size
        cap = self._bucket_bytes if self._bucket_bytes is not None \
            else grad_bucket_bytes()
        if size >= cap:
            self._dispatch(key)

    @staticmethod
    def _reducible(g):
        from ..gluon import fused_step as _fs
        from ..ndarray import sparse as _sp

        return not isinstance(g, _sp.BaseSparseNDArray) and \
            not _fs.has_tracer([g._data])

    def _dispatch(self, key):
        from .. import parallel
        from ..resilience import faults as _faults

        bucket = self._pending.pop(key, [])
        self._pending_bytes.pop(key, None)
        if not bucket:
            return
        # registered fault point: a failed mid-backward collective.
        # Raises into backward (or the step-time flush) with the
        # bucket already popped — exactly the partial-round state a
        # real collective failure leaves; recovery goes through
        # abandon() (AutoResume restore / the load_states boundary).
        _faults.maybe_fail("grad_bucket_dispatch")
        datas = [d for _, d in bucket]
        nbytes = sum(d.size * d.dtype.itemsize for d in datas)
        with _telem.span("pipeline.grad_bucket", cat="pipeline",
                         grads=len(bucket), bytes=nbytes):
            reduced = parallel.all_reduce_coalesced(
                datas, reduce_fn=self._reduce_fn)
        for (g, captured), r in zip(bucket, reduced):
            self._spec[id(g)] = (captured, _raw(r))
        _count("grad_buckets")
        _count("grad_bucket_bytes", nbytes)
        _count("grad_async_grads", len(bucket))

    def abandon(self):
        """Drop all per-round state without dispatching or binding —
        the step-time path declined async sync this round (the knob
        flipped off between backward and step()). Speculative results
        are discarded; the grads themselves were never modified, so the
        coalesced-at-step path reduces the true values. Also re-arms
        the per-round knob read, so later backwards stop dispatching."""
        self._pending.clear()
        self._pending_bytes.clear()
        self._spec.clear()
        self._round_enabled = None

    # -- step-time flush ----------------------------------------------------

    def flush(self, grads):
        """Finish the round: dispatch partial buckets, then bind every
        grad in ``grads`` to its reduced value — the speculative result
        when the buffer is untouched since dispatch, a fresh reduction
        otherwise (late accumulation / overwrite / a param backward
        never reached this round). Exactly-once per round per grad."""
        from .. import parallel

        for key in list(self._pending):
            self._dispatch(key)
        spec, self._spec = self._spec, {}
        self._round_enabled = None
        todo = []
        for g in grads:
            ent = spec.get(id(g))
            if ent is not None and g._data is ent[0]:
                g._data = ent[1]
            else:
                if ent is not None:
                    _count("grad_stale_discards")
                todo.append(g)
        if todo:
            with _telem.span("pipeline.grad_flush", cat="pipeline",
                             grads=len(todo)):
                reduced = parallel.all_reduce_coalesced(
                    [g._data for g in todo], reduce_fn=self._reduce_fn)
            for g, r in zip(todo, reduced):
                g._data = _raw(r)
            _count("grad_flush_grads", len(todo))
        return len(todo)


def _raw(x):
    """The jnp array behind an all_reduce_coalesced result (NDArray when
    the inputs were NDArrays, raw otherwise)."""
    from ..ndarray import NDArray

    return x.data if isinstance(x, NDArray) else x
