"""DeviceFeed: prefetching device-feed iterator.

Wraps any batch source — a DataIter (``io.py``), a gluon DataLoader, or
a plain iterable/generator — and keeps ``MXNET_DEVICE_PREFETCH`` batches
staged ON DEVICE ahead of the consuming step:

- a background worker thread pulls batches from the source (so host
  decode/augment/batchify runs off the step loop's critical path) and
  stages every array leaf with an async ``jax.device_put`` (so the H2D
  transfer of batch k+1 rides PJRT's copy stream while the compiled
  step consumes batch k — the reference's PrefetcherIter overlap,
  src/io/iter_prefetcher.h:142, extended through the transfer);
- the bounded queue holds at most ``depth`` staged batches (one more
  may be mid-staging in the worker), so prefetch never balloons HBM;
- staged buffers are freshly allocated by ``device_put`` and uniquely
  referenced by the queue item — safe to donate to a consuming
  executable once the caller owns the batch (donation-friendly);
- a source exception is captured and re-raised in the CONSUMER at the
  point of ``next()`` (never lost in the thread, never a deadlock), and
  ``close()``/``reset()`` drain the worker even when it is blocked on a
  full queue;
- ``depth=0`` (or ``MXNET_DEVICE_PREFETCH=0``) degrades to synchronous
  inline staging: no thread, no queue, bit-for-bit the behavior of the
  unpipelined loop.

Counters (``pipeline_counters()``): a ``prefetch_hit`` is a ``next()``
that found its batch already staged; a ``prefetch_stall`` had to wait on
the worker, and the wait time accumulates into ``prefetch_stall_s`` —
the time the step loop (and therefore the device) sat idle on data.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

import numpy as onp

from ..resilience import faults as _faults
from ..telemetry import tracer as _telem
from . import (_count, _count_set, prefetch_depth)

__all__ = ["DeviceFeed"]


# end-of-stream marker: a dedicated object, NOT None — a buggy source
# yielding None must surface as a None batch in the consumer, never as
# a silently truncated epoch
_END = object()


class _Raised:
    """Wrapper distinguishing a propagated source exception from a
    batch that happens to BE an Exception instance."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class _Epoch:
    """One pass's worker state: queue + stop flag + thread, all local to
    the generation so a worker from before a reset can never deliver
    stale batches (or its end-of-stream sentinel) into the new pass."""

    __slots__ = ("q", "stop", "thread")

    def __init__(self, depth):
        self.q = _queue.Queue(maxsize=depth)
        self.stop = threading.Event()
        self.thread = None


class DeviceFeed:
    """Prefetching device-feed iterator (see module docstring).

    ``for batch in feed`` mirrors ``for batch in source`` with every
    array leaf resident on ``device``; numpy leaves come back as device
    NDArrays. A finished (or failed) feed re-arms a fresh pass on the
    next ``iter()`` — call ``source.reset()`` (or ``feed.reset()``,
    which forwards) first when the source is a rewindable DataIter.
    """

    def __init__(self, source, depth=None, device=None):
        self.source = source
        self.batch_size = getattr(source, "batch_size", None)
        self._depth = prefetch_depth() if depth is None \
            else max(0, int(depth))
        self._device = device
        self._epoch = None       # active _Epoch (async mode)
        self._sync_it = None     # active source iterator (passthrough)
        self._finished = False
        self._t_first = None     # first-next timestamp of this pass
        self._served = 0         # batches delivered this pass (cursor)
        self._skip_base = 0      # batches skip()'d before this pass
        _count_set("prefetch_depth", self._depth)

    # -- staging ------------------------------------------------------------

    def _stage_leaf(self, x):
        import jax

        from ..ndarray import NDArray

        # registered fault point: a failed H2D transfer / staging OOM.
        # Fires in the worker thread; the exception propagates to the
        # consumer's next() exactly like a real device_put failure.
        _faults.maybe_fail("device_put")
        if isinstance(x, NDArray):
            return NDArray(jax.device_put(x.data, self._device))
        if isinstance(x, (onp.ndarray, jax.Array)):
            return NDArray(jax.device_put(x, self._device))
        return x

    def _stage(self, item):
        """Map ``_stage_leaf`` over the batch structure (DataBatch /
        list / tuple / dict / bare array), preserving the container."""
        from ..io.io import DataBatch

        if isinstance(item, DataBatch):
            return DataBatch(
                data=[self._stage_leaf(d) for d in (item.data or [])],
                label=[self._stage_leaf(l) for l in (item.label or [])],
                pad=item.pad, index=item.index,
                bucket_key=item.bucket_key,
                provide_data=item.provide_data,
                provide_label=item.provide_label)
        if isinstance(item, (list, tuple)):
            return type(item)(self._stage(v) for v in item)
        if isinstance(item, dict):
            return {k: self._stage(v) for k, v in item.items()}
        return self._stage_leaf(item)

    # -- worker -------------------------------------------------------------

    @staticmethod
    def _put(ep, item):
        """Bounded put that ``close()`` can always unblock; False when
        stopped before the item landed."""
        while not ep.stop.is_set():
            try:
                ep.q.put(item, timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self, ep):
        try:
            for batch in self.source:
                if ep.stop.is_set():
                    return
                # its own lane in the trace: staging runs on the
                # device-feed thread, parallel to the consumer's step
                # spans — the round-11 overlap, visible
                with _telem.span("pipeline.prefetch_stage",
                                 cat="pipeline"):
                    staged = self._stage(batch)
                if not self._put(ep, staged):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._put(ep, _Raised(e))
        finally:
            self._put(ep, _END)

    @property
    def position(self):
        """The epoch offset of the NEXT batch — ``skip()``'d prefix
        plus batches delivered this pass. This is the step cursor a
        CheckpointManager snapshot records (``cursor={"step": ...}``),
        so it must stay absolute across a skip-based resume: a second
        crash in the same epoch then resumes at the true offset
        instead of replaying the prefix twice."""
        return self._skip_base + self._served

    def skip(self, n):
        """Advance the SOURCE past ``n`` batches without staging them
        (resume repositioning before iteration starts); ``position``
        counts them. Only valid on a one-shot source (generator /
        fresh iterator): a re-iterable source would rewind when the
        worker later calls ``iter`` on it, silently undoing the skip —
        that raises instead."""
        if n <= 0:
            return self
        if self._epoch is not None or self._sync_it is not None:
            raise RuntimeError("DeviceFeed.skip() must run before "
                               "iteration starts")
        it = iter(self.source)
        if it is not iter(self.source):
            raise RuntimeError(
                "DeviceFeed.skip() needs a one-shot source (iter(src) "
                "is src); re-iterable sources would rewind when the "
                "feed starts — slice the source instead")
        for _ in range(n):
            next(it)
        self._skip_base += n
        return self

    def _start(self):
        ep = _Epoch(self._depth)
        ep.thread = threading.Thread(
            target=self._worker, args=(ep,), daemon=True,
            name="device-feed")
        self._epoch = ep
        self._finished = False
        self._t_first = None
        self._served = 0
        ep.thread.start()

    # -- iteration ----------------------------------------------------------

    def __iter__(self):
        if self._finished:
            # previous pass ended (exhausted or failed): re-arm a fresh
            # one over the source's current position
            self.close()
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        if self._depth <= 0:
            return self._next_sync()
        if self._epoch is None:
            self._start()
        ep = self._epoch
        t0 = time.perf_counter()
        if self._t_first is None:
            self._t_first = t0
        stalled = ep.q.empty()
        tm0 = time.monotonic() if _telem.tracing() else 0.0
        item = ep.q.get()
        if tm0:
            _telem.emit_span("pipeline.feed_wait", "pipeline", tm0,
                             time.monotonic(), stalled=stalled)
        wait = time.perf_counter() - t0
        if item is _END:
            self._end_pass()
            raise StopIteration
        if isinstance(item, _Raised):
            _count("feed_errors")
            self._end_pass()
            raise item.exc
        if stalled:
            _count("prefetch_stalls")
            _count("prefetch_stall_s", wait)
        else:
            _count("prefetch_hits")
        _count("prefetch_batches")
        self._served += 1
        return item

    next = __next__

    def _next_sync(self):
        """depth=0 passthrough: inline pull + stage, no thread."""
        if self._sync_it is None:
            self._sync_it = iter(self.source)
            self._t_first = time.perf_counter()
            self._served = 0
        try:
            item = self._stage(next(self._sync_it))
        except StopIteration:
            self._end_pass()
            raise
        self._served += 1
        return item

    def _end_pass(self):
        if self._t_first is not None:
            _count("feed_active_s", time.perf_counter() - self._t_first)
            self._t_first = None
        self._finished = True
        self._epoch = None
        self._sync_it = None

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        """Stop and join the worker, discarding staged batches.
        Idempotent; safe mid-pass (a worker blocked on the full queue is
        drained out, never deadlocked) and from ``__del__``."""
        ep = self._epoch
        self._epoch = None
        self._sync_it = None
        self._skip_base = 0
        if self._t_first is not None:
            _count("feed_active_s", time.perf_counter() - self._t_first)
            self._t_first = None
        self._finished = False
        if ep is None:
            return
        ep.stop.set()
        # every get() frees a slot; _put re-checks stop each 0.2s
        while ep.thread.is_alive():
            try:
                ep.q.get(timeout=0.1)
            except _queue.Empty:
                pass
        ep.thread.join()

    def reset(self):
        """DataIter-style rewind: drain the worker, reset the source,
        re-arm lazily on the next ``next()``."""
        self.close()
        reset = getattr(self.source, "reset", None)
        if reset is not None:
            reset()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            from ..utils import locks as _locks

            # finalizers interleave arbitrarily; the witness must not
            # attribute the engine waits in close() to whatever locks
            # the interrupted thread happened to hold
            with _locks.exempt("gc finalizer on unreachable feed"):
                self.close()
        except Exception:  # graft-lint: allow(L501)
            pass

    def __len__(self):
        return len(self.source)
