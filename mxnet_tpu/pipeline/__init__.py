"""Async end-to-end training pipeline.

Rounds 6-9 made per-step device compute cheap (eager jit cache, one
donated XLA executable per optimizer step, persistent compile cache), so
the epoch loop is host-bound: each step serializes host batch prep →
``device_put`` → dispatch → gradient all-reduce → optimizer update. This
package overlaps those stages — the step-loop analog of TVM's
latency-hiding-by-scheduling, and of the reference's PrefetcherIter +
kvstore-async machinery (src/io/iter_prefetcher.h,
kvstore_dist_server.h):

- ``DeviceFeed`` (device_feed.py): a prefetching device-feed iterator
  wrapping any DataIter / DataLoader / iterable. A background thread
  pulls batches from the source and stages them onto the device with
  async ``jax.device_put``, keeping ``MXNET_DEVICE_PREFETCH`` batches
  double-buffered ahead of the consuming step. Staged buffers are
  freshly allocated and uniquely referenced (donation-friendly).
- ``AsyncGradReducer`` (grad_sync.py): bucketed dispatch-as-ready
  gradient all-reduce. Grads are bucketed by dtype/size and each
  bucket's collective is dispatched the moment backward writes its
  grads (via the autograd grad-ready hook), overlapping communication
  with the remaining backward instead of one barrier at ``step()``.
  ``MXNET_ASYNC_GRAD_SYNC`` gates it; values are bit-identical to the
  coalesced-at-step path (elementwise sums commute with bucketing).
- async kvstore pushes (``MXNET_KVSTORE_ASYNC``, kvstore.py): local
  pushes apply on the background applier thread so the server-side
  updater overlaps the next forward.

``pipeline_counters()`` surfaces prefetch depth/hits/stalls, the
accumulated stall ("engine idle") time, the measured overlap ratio, and
the grad-sync/kvstore dispatch counts; the counters ride
``profiler.dump()`` and the ``PIPELINE`` runtime feature mirrors the
master knob. See docs/PIPELINE.md.
"""
from __future__ import annotations

from ..telemetry import metrics as _telemetry

__all__ = ["DeviceFeed", "AsyncGradReducer", "pipeline_enabled",
           "prefetch_depth", "async_grad_sync_enabled",
           "kvstore_async_enabled", "grad_bucket_bytes",
           "pipeline_counters", "reset_pipeline_counters"]


def prefetch_depth():
    """MXNET_DEVICE_PREFETCH (default 2); 0 = synchronous passthrough.
    Read at feed construction so tests/benchmarks toggle per instance."""
    from .. import env as _env

    return max(0, _env.get_int("MXNET_DEVICE_PREFETCH", 2))


def pipeline_enabled():
    """The PIPELINE runtime feature: prefetch armed (depth > 0)."""
    return prefetch_depth() > 0


def async_grad_sync_enabled():
    """MXNET_ASYNC_GRAD_SYNC (default on): dispatch-as-ready bucketed
    gradient all-reduce; 0 = one coalesced collective at step() time."""
    from .. import env as _env

    return _env.get_bool("MXNET_ASYNC_GRAD_SYNC", True)


def grad_bucket_bytes():
    """MXNET_GRAD_BUCKET_KB (default 512 KiB) in bytes."""
    from .. import env as _env

    return max(1, _env.get_int("MXNET_GRAD_BUCKET_KB", 512)) * 1024


def kvstore_async_enabled():
    """MXNET_KVSTORE_ASYNC — OPT-IN (default 0) background-thread
    application of local kvstore pushes."""
    from .. import env as _env

    return _env.get_bool("MXNET_KVSTORE_ASYNC", False)


# ---------------------------------------------------------------------------
# counters (thread-safe: feed workers, the consumer, and kvstore's
# applier thread all tick them). Since round 18 the dict is a
# registry-owned telemetry.CounterFamily — same mutation idiom, but the
# family is scrapeable from the unified Prometheus exposition and rides
# telemetry.dump_trace() counter samples.


def _zero_counters():
    return {
        # device feed
        "prefetch_depth": 0,       # last configured depth
        "prefetch_batches": 0,     # batches staged onto device
        "prefetch_hits": 0,        # batch already staged when asked for
        "prefetch_stalls": 0,      # consumer had to wait on the worker
        "prefetch_stall_s": 0.0,   # total consumer wait = device idle gap
        "feed_active_s": 0.0,      # wall time feeds spent being consumed
        "feed_errors": 0,          # source exceptions propagated
        # async grad sync
        "grad_buckets": 0,         # collectives dispatched mid-backward
        "grad_bucket_bytes": 0,    # bytes those collectives covered
        "grad_async_grads": 0,     # grads reduced ahead of step()
        "grad_flush_grads": 0,     # grads reduced at the step() flush
        "grad_stale_discards": 0,  # speculative reductions re-done
        # async kvstore
        "kvstore_async_pushes": 0,
    }


_COUNTERS = _telemetry.counter_family("pipeline", _zero_counters())


def _count(name, delta=1):
    _COUNTERS.add(name, delta)


def _count_set(name, value):
    _COUNTERS.set(name, value)


def pipeline_counters():
    """Live pipeline counters plus two derived metrics: ``engine_idle_s``
    (total time the consuming step loop sat waiting on data — the gap
    the prefetcher exists to close) and ``overlap_ratio`` (fraction of
    the feed's consumption window NOT spent stalled; 1.0 = the source
    was always ahead of the step)."""
    out = _COUNTERS.snapshot()
    out["engine_idle_s"] = out["prefetch_stall_s"]
    active = out["feed_active_s"]
    out["overlap_ratio"] = (
        max(0.0, 1.0 - out["prefetch_stall_s"] / active) if active > 0
        else 0.0)
    return out


def reset_pipeline_counters():
    """Zero every counter (tests, benchmarks)."""
    _COUNTERS.reset()


from .device_feed import DeviceFeed  # noqa: E402
from .grad_sync import AsyncGradReducer  # noqa: E402
