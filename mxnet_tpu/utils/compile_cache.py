"""Persistent compile-artifact cache + shape-bucketing retrace elimination.

The in-memory executable caches (the eager-dispatch cache in
``ndarray/registry.py`` and the fused train-step cache in
``gluon/fused_step.py``) made the hot path fast *once compiled*, but both
die with the process: every restart re-pays full trace + XLA-compile
cost, and shape variation (bucketed RNN/NLP batches, the last partial
batch, ResizeIter) triggers a retrace storm. This module is the layer
that spans both caches and kills those two costs (the compile-cost
amortization lever TVM, arxiv 1802.04799, and the XLA fusion study,
arxiv 2301.13062, identify as decisive once kernel quality is fixed):

**Disk second tier.** ``fingerprint()`` derives a stable key from the
in-memory cache key — (op/graph fingerprint, avals, donation mask, AMP
version) — salted with the jax/jaxlib/backend/framework versions and a
format version. ``disk_store()`` serializes an AOT-compiled executable
(``jax.jit(...).lower(...).compile()`` →
``jax.experimental.serialize_executable``) under that fingerprint;
``disk_load()`` deserializes it in a later process, so a warm start
reaches steady state without recompiling. Corrupt or version-mismatched
entries are treated as misses (and removed). Entries whose output pytree
contains live functions (the ``jax.vjp`` pullback of recording-mode
dispatch entries) cannot serialize — those count as ``serialize_skips``
and fall back to jax's own persistent compilation cache, which
``_ensure_jax_fallback_cache`` points at the same directory (XLA-compile
cost skipped; tracing still paid).

**Retrace accounting.** ``counting_jit()`` is the blessed ``jax.jit``
wrapper (the ``graft_lint`` ``jit-nocache`` rule flags raw call sites):
it drops a host-side counter tick into the traced body, so *actual*
traces — not calls — are counted, framework-wide. Shape-bucketing wins
and warm-start wins both show up as a flat ``retraces`` counter.

**Shape bucketing.** ``plan_bucketing()`` rounds the batch axis of
eligible op dispatches up to a bucket boundary (``MXNET_SHAPE_BUCKETS``:
``pow2`` rounding, or ``mult:N``), so a variable-length stream reuses a
few bucket executables instead of retracing per batch size. Only ops in
the ``_BATCH_SAFE`` table are bucketed — ops whose output rows depend
only on the matching input rows, so padding rows with zeros and slicing
the output back is bitwise row-identical — and only outside autograd
recording. The dispatch cache pads inputs before key lookup and slices
outputs after execution (``pad_batch``/``slice_batch``).

Knobs (``env.py``): ``MXNET_COMPILE_CACHE=0`` disables the disk tier,
``MXNET_COMPILE_CACHE_DIR`` points it somewhere other than
``$MXNET_HOME/compile_cache``, ``MXNET_SHAPE_BUCKETS`` enables
bucketing. Counters surface via ``profiler.compile_cache_counters()``
and the ``COMPILE_CACHE`` runtime feature.
"""
from __future__ import annotations

import functools
import hashlib
import os
import pickle
import threading
import warnings

import numpy as onp

from ..telemetry import metrics as _telemetry
from ..telemetry import tracer as _telem

__all__ = ["cache_enabled", "cache_dir", "fingerprint", "disk_load",
           "disk_store", "counting_jit", "note_retrace", "aot_compile",
           "load_or_compile", "GuardedCompiled", "bucket_spec",
           "bucket_size", "plan_bucketing", "pad_batch", "slice_batch",
           "compile_cache_stats", "reset_compile_cache_counters"]

FORMAT_VERSION = 1


def _zero_stats():
    return {"disk_hits": 0, "disk_misses": 0, "disk_writes": 0,
            "disk_corrupt": 0, "disk_evicted": 0, "prunes": 0,
            "serialize_skips": 0, "retraces": 0,
            "bucketed_calls": 0, "padded_rows": 0, "true_rows": 0}


# registry-owned since round 18; the registered "compile_cache" probe
# (compile_cache_stats, + derived pad_ratio) shadows it on read surfaces
_STATS = _telemetry.counter_family("compile_cache", _zero_stats())


def _bump(name, n=1):
    _STATS.add(name, n)


def compile_cache_stats():
    """Disk-tier + retrace + bucketing counters (profiler surface).

    ``pad_ratio`` is total padded rows / total true rows over all
    bucketed dispatches (0.0 when nothing was bucketed)."""
    st = _STATS.snapshot()
    st["pad_ratio"] = (st["padded_rows"] / st["true_rows"]
                       if st["true_rows"] else 0.0)
    st["enabled"] = cache_enabled()
    return st


def reset_compile_cache_counters():
    """Zero the counters (tests, benchmarks). Does not touch the disk
    cache contents — remove the directory for that."""
    _STATS.reset()


# ---------------------------------------------------------------------------
# knobs

def cache_enabled():
    """MXNET_COMPILE_CACHE knob (default on); 0 disables the disk tier
    (the in-memory LRUs are unaffected). Read per use so tests can
    toggle without reimport."""
    from .. import env as _env

    return _env.get_bool("MXNET_COMPILE_CACHE", True)


def cache_dir():
    """MXNET_COMPILE_CACHE_DIR, defaulting to $MXNET_HOME/compile_cache
    ($MXNET_HOME defaults to ~/.mxnet, like the model store)."""
    from .. import env as _env

    d = _env.get_str("MXNET_COMPILE_CACHE_DIR")
    if d:
        return d
    home = _env.get_str("MXNET_HOME",
                        os.path.join(os.path.expanduser("~"), ".mxnet"))
    return os.path.join(home, "compile_cache")


_JAX_FALLBACK = {"dir": None}


def _ensure_jax_fallback_cache(directory):
    """Point jax's own persistent compilation cache at our directory
    (best effort). It keys on the lowered HLO, so it only kicks in
    after tracing — but that still covers the entries this tier cannot
    serialize (recording-mode vjp pairs, executor jits): their XLA
    compile cost is skipped on a warm start even though the trace cost
    is paid again."""
    if _JAX_FALLBACK["dir"] == directory:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", directory)
        # only compiles worth the disk round-trip: caching every eager
        # micro-prim (min_compile_time 0) measurably TAXES the hot path
        # with serialize+write per prim — the .mxc tier already covers
        # whole dispatch executables, this tier is for the big traced
        # programs (CachedOp, executor, recording-entry first hits)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.05)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _JAX_FALLBACK["dir"] = directory
    except Exception:
        _JAX_FALLBACK["dir"] = directory  # don't retry per call


# ---------------------------------------------------------------------------
# fingerprinting

class _Unstable(Exception):
    """A key component has no process-stable canonical form."""


def _canon(v):
    """Process-stable canonical form of a cache-key component.

    Only types whose repr/identity is reproducible across processes are
    admitted — anything else (live functions, closures, arbitrary
    objects whose repr embeds an address) raises ``_Unstable`` and the
    key is simply not persisted. Collision-safety beats coverage here:
    an over-eager canonicalization that maps two different computations
    to one fingerprint would serve the wrong executable."""
    if v is None or isinstance(v, (bool, int, str, bytes)):
        return v
    if isinstance(v, float):
        return ("f", v.hex())
    if isinstance(v, complex):
        return ("c", v.real.hex(), v.imag.hex())
    if isinstance(v, type):
        return ("cls", v.__module__, v.__qualname__)
    if isinstance(v, onp.dtype):
        return ("dt", str(v))
    if isinstance(v, (onp.bool_, onp.integer, onp.floating)):
        return ("np", str(v.dtype), v.item())
    if isinstance(v, slice):
        return ("sl", _canon(v.start), _canon(v.stop), _canon(v.step))
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(_canon(x) for x in v)
    if isinstance(v, dict):
        return ("d",) + tuple(sorted((str(k), _canon(x))
                                     for k, x in v.items()))
    if isinstance(v, frozenset):
        return ("fs",) + tuple(sorted(repr(_canon(x)) for x in v))
    # jnp dtype objects used in avals are numpy dtypes; bfloat16 is an
    # extension type with a stable name
    name = getattr(v, "name", None)
    if name is not None and type(v).__name__ in ("dtype", "type"):
        return ("dt", str(name))
    raise _Unstable(type(v).__name__)


def _salt():
    import jax
    import jaxlib

    from .. import __version__ as fw_version

    return (FORMAT_VERSION, jax.__version__, jaxlib.__version__,
            jax.default_backend(), fw_version)


def fingerprint(kind, key, code_of=()):
    """Stable hex fingerprint of an in-memory cache key, or None when a
    component has no process-stable form (that entry just stays
    memory-only). ``kind`` namespaces the producing cache ('dispatch',
    'fused_step', ...). ``code_of`` lists the functions whose BODIES the
    cached executable was traced from (op body, optimizer kernel, the
    executable builder): their bytecode digests salt the fingerprint, so
    editing an implementation without bumping any version invalidates
    its disk entries instead of silently serving the old computation —
    the cache key alone carries only the op NAME."""
    try:
        canon = (_salt(), str(kind), _canon(key),
                 tuple(code_digest(f) for f in code_of))
    except _Unstable:
        return None
    return hashlib.sha256(repr(canon).encode()).hexdigest()


_CODE_DIGESTS = {}  # weak-keyed via functions' __code__ identity


def code_digest(fn):
    """Digest of a function's bytecode, recursing into nested code
    objects (closures built inside it) — process-stable for identical
    source, different for any edited body. Defaults and closure cells
    are NOT covered (they are runtime values; key material like static
    hyperparameters must ride the cache key itself)."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return ("nocode", getattr(fn, "__module__", ""),
                getattr(fn, "__qualname__", repr(type(fn))))
    cached = _CODE_DIGESTS.get(code)
    if cached is not None:
        return cached
    h = hashlib.sha256()

    def feed(c):
        h.update(c.co_code)
        h.update(repr((c.co_names, c.co_varnames,
                       c.co_consts and tuple(
                           x for x in c.co_consts
                           if isinstance(x, (type(None), bool, int, float,
                                             complex, str, bytes, tuple))
                       ))).encode())
        for const in c.co_consts:
            if isinstance(const, type(c)):
                feed(const)

    feed(code)
    digest = ("code", h.hexdigest())
    _CODE_DIGESTS[code] = digest
    return digest


# ---------------------------------------------------------------------------
# disk tier

def _entry_path(fp):
    return os.path.join(cache_dir(), fp + ".mxc")


def disk_load(fp):
    """Load a serialized executable: (compiled, meta) or None. Any
    failure — missing file, truncated pickle, version drift, pjrt
    deserialize error — is a miss; corrupt files are removed best
    effort so they don't fail every future start."""
    if fp is None or not cache_enabled():
        return None
    with _telem.span("compile_cache.disk_load", cat="io",
                     fp=fp[:16]) as sp:
        out = _disk_load_inner(fp)
        sp.set(hit=out is not None)
        return out


def _disk_load_inner(fp):
    _ensure_jax_fallback_cache(cache_dir())
    path = _entry_path(fp)
    if not os.path.exists(path):
        _bump("disk_misses")
        return None
    # registered fault point (resilience/faults.py): a transient IO
    # failure degrades to a MISS — outside the corruption handler
    # below, which deletes the file: an injected transient must not
    # destroy a valid cache entry (chaos drills would erode the warm
    # start they are testing)
    from ..resilience import faults as _faults

    try:
        _faults.maybe_fail("compile_cache_io")
    except Exception:
        _bump("disk_misses")
        return None
    try:
        with open(path, "rb") as f:
            env = pickle.load(f)
        if env.get("format") != FORMAT_VERSION or env.get("salt") != _salt():
            raise ValueError("compile-cache version mismatch")
        from jax.experimental import serialize_executable as _se

        compiled = _se.deserialize_and_load(env["payload"], env["in_tree"],
                                            env["out_tree"])
    except Exception:
        _bump("disk_corrupt")
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    try:
        os.utime(path)  # mark recency: pruning evicts oldest-used first
    except OSError:
        pass
    _bump("disk_hits")
    return compiled, env.get("meta", {})


def disk_store(fp, compiled, meta=None, key_repr=None):
    """Serialize an AOT-compiled executable under ``fp``; True on a
    completed write. Unserializable executables (live functions in the
    output pytree — e.g. vjp pullbacks) count as ``serialize_skips``;
    IO problems are silent best-effort (a cache must never break the
    step loop)."""
    if fp is None or not cache_enabled():
        return False
    with _telem.span("compile_cache.disk_store", cat="io",
                     fp=fp[:16]) as sp:
        ok = _disk_store_inner(fp, compiled, meta, key_repr)
        sp.set(written=ok)
        return ok


def _disk_store_inner(fp, compiled, meta, key_repr):
    _ensure_jax_fallback_cache(cache_dir())
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps({"format": FORMAT_VERSION, "salt": _salt(),
                             "meta": dict(meta or {}),
                             "key_repr": key_repr, "payload": payload,
                             "in_tree": in_tree, "out_tree": out_tree})
    except Exception:
        _bump("serialize_skips")
        return False
    try:
        from ..resilience import faults as _faults

        _faults.maybe_fail("compile_cache_io")
        directory = cache_dir()
        os.makedirs(directory, exist_ok=True)
        path = _entry_path(fp)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)  # atomic: concurrent writers race safely
    except Exception:
        # broad on purpose: ANY cache-write failure (disk full, perm,
        # an injected fault) is a skipped write, never a broken step
        return False
    _bump("disk_writes")
    _maybe_prune(directory)
    return True


_PRUNE_EVERY = 32
_prune_tick = [0]


def _maybe_prune(directory):
    """Bound the on-disk tier (the in-memory tiers are LRUs; without
    this the directory grows one serialized executable per fingerprint
    forever — including never-probed stale-salt entries after version
    bumps). Every ``_PRUNE_EVERY``-th write, if the ``.mxc`` total
    exceeds MXNET_COMPILE_CACHE_MAX_MB, the oldest-used entries (mtime:
    refreshed on every load) are removed down to 80% of the cap."""
    _prune_tick[0] += 1
    if _PRUNE_EVERY > 1 and _prune_tick[0] % _PRUNE_EVERY != 1:
        return
    from .. import env as _env

    cap_mb = _env.get_int("MXNET_COMPILE_CACHE_MAX_MB", 1024)
    if cap_mb <= 0:
        return  # 0 = unbounded, explicitly
    entries = []
    try:
        with os.scandir(directory) as it:
            for e in it:
                if not e.name.endswith(".mxc"):
                    continue
                try:
                    st = e.stat()
                except OSError:
                    continue  # pruned/replaced by a concurrent process
                entries.append((st.st_mtime, st.st_size, e.path))
    except OSError:
        return  # directory unreadable/gone: nothing to bound
    total = sum(sz for _, sz, _ in entries)
    cap = cap_mb * 1024 * 1024
    if total <= cap:
        return
    _bump("prunes")
    entries.sort()  # oldest-used first
    for _, sz, path in entries:
        try:
            os.remove(path)
        except OSError:
            continue  # a concurrent pruner won the race for this one
        _bump("disk_evicted")
        total -= sz
        if total <= cap * 0.8:
            break


# ---------------------------------------------------------------------------
# retrace-counted jit + AOT helpers

def note_retrace(label=None):
    """Count one actual trace (called from inside traced bodies, so it
    fires at trace time only — cached executions never reach it)."""
    del label  # per-label breakdown can ride later without API change
    _bump("retraces")


def counting_jit(fun, label=None, **jit_kwargs):
    """``jax.jit`` with retrace accounting — the blessed way to jit
    inside ``mxnet_tpu`` (the ``graft_lint`` ``jit-nocache`` rule flags
    raw ``jax.jit`` call sites). The wrapper ticks the ``retraces``
    counter from inside the traced body: jit-cache hits never re-enter
    the Python body, so the counter measures traces, not calls."""
    import jax

    if cache_enabled():
        # even entries this tier can't serialize (vjp pairs, executor
        # closures) get their XLA-compile cost cached across processes
        _ensure_jax_fallback_cache(cache_dir())
    name = label or getattr(fun, "__name__", "fn")

    @functools.wraps(fun)
    def counted(*args, **kwargs):
        note_retrace(name)
        return fun(*args, **kwargs)

    return jax.jit(counted, **jit_kwargs)  # graft-lint: allow(jit-nocache)


def aot_compile(jitted, *args, **kwargs):
    """``jitted.lower(*args).compile()`` with backend donation warnings
    suppressed (CPU warns that donation is unimplemented at lowering
    time; the hint is best-effort by design). Returns the ``Compiled``
    handle — the serializable artifact the disk tier stores."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return jitted.lower(*args, **kwargs).compile()


def load_or_compile(fp, jitted, args, meta=None):
    """The whole warm-start story as one call: deserialize the disk
    entry under ``fp`` if present, else AOT-compile ``jitted`` over
    ``args`` (avals or concrete arrays) and persist it. Returns
    ``(fn, meta, from_disk)`` where ``fn`` is a :class:`GuardedCompiled`
    (any aval mismatch or stale artifact degrades to the jit path
    rather than erroring the caller). ``meta`` may be a dict or a
    zero-arg callable evaluated AFTER the fresh compile — so metadata
    derived at trace time (output arity, tree structure) can ride the
    envelope for warm processes that never trace. ``fp=None`` (an
    unstable key) compiles memory-only."""
    loaded = disk_load(fp)
    if loaded is not None:
        compiled, m = loaded
        return GuardedCompiled(compiled, jitted), m, True
    compiled = aot_compile(jitted, *args)
    m = dict(meta() if callable(meta) else (meta or {}))
    disk_store(fp, compiled, meta=m)
    return GuardedCompiled(compiled, jitted), m, False


class GuardedCompiled:
    """Callable facade over an AOT/deserialized ``Compiled`` with a
    jitted fallback: ``Compiled`` objects are specialized to exact
    input avals (including weak_type and sharding), so any mismatch —
    or a stale on-disk artifact — degrades permanently to the plain
    ``jax.jit`` path instead of erroring the caller's step loop."""

    __slots__ = ("_compiled", "_jfn")

    def __init__(self, compiled, jfn):
        self._compiled = compiled
        self._jfn = jfn

    def __call__(self, *args):
        compiled = self._compiled
        if compiled is not None:
            try:
                return compiled(*args)
            except Exception:
                self._compiled = None
        return self._jfn(*args)


# ---------------------------------------------------------------------------
# shape bucketing

_SPEC_CACHE = {}


def bucket_spec():
    """Parsed MXNET_SHAPE_BUCKETS policy: None (off, the default),
    ('pow2',) or ('mult', N). '1' enables the default pow2 policy."""
    from .. import env as _env

    raw = _env.get_str("MXNET_SHAPE_BUCKETS")
    if raw is None:
        return None
    spec = _SPEC_CACHE.get(raw)
    if spec is None:
        spec = _parse_spec(raw)
        _SPEC_CACHE[raw] = spec
    return spec or None


def _parse_spec(raw):
    raw = raw.strip()
    if raw in ("", "0", "false", "False", "off"):
        return ()
    if raw in ("1", "pow2", "true", "True", "on"):
        return ("pow2",)
    if raw.startswith("mult:"):
        try:
            n = int(raw.split(":", 1)[1])
        except ValueError:
            n = 0
        if n > 1:
            return ("mult", n)
    import logging

    logging.warning("invalid MXNET_SHAPE_BUCKETS=%r; bucketing disabled "
                    "(expected 0 | pow2 | mult:N)", raw)
    return ()


def bucket_size(n, spec):
    """Bucket boundary for a batch of ``n`` rows under ``spec``."""
    if n <= 1:
        return n
    if spec[0] == "pow2":
        b = 1
        while b < n:
            b <<= 1
        return b
    return -(-n // spec[1]) * spec[1]  # mult:N — round up to multiple


# op -> bucketing rule. "ew": elementwise/broadcast — every max-rank
# operand whose axis 0 equals the batch is padded, lower-rank /
# broadcast (axis0 == 1) operands pass through, and output rows are
# independent per input row. ("row", (slots...), guard): only the given
# operand slots carry the batch on axis 0 (rank >= 2 required — on a
# 1-D operand axis 0 is the data/contraction axis, not a batch);
# ``guard(config, datas)`` sees the op's full config — positional
# literals bound through the op signature included — and vetoes
# configs that mix rows (e.g. transposed dot, softmax over axis 0).
# Everything NOT in this table is never bucketed: padding is only
# row-bitwise-identical when no output row reads another input row.

def _softmax_axis_ok(config, datas):
    if config.get("use_length") or config.get("length") is not None:
        return False
    axis = config.get("axis", -1)
    if not isinstance(axis, int):
        return False
    # resolve against rank: axis=-2 on 2-D (or any alias of axis 0)
    # normalizes over the batch axis and padded rows would leak into
    # the denominator
    return axis % datas[0].ndim != 0


def _dot_rowwise(config, datas):
    return not config.get("transpose_a", False)


def _fc_flatten(config, datas):
    return bool(config.get("flatten", True))


_BATCH_SAFE = {
    # elementwise / broadcast arithmetic
    "broadcast_add": "ew", "broadcast_sub": "ew", "broadcast_mul": "ew",
    "broadcast_div": "ew", "broadcast_power": "ew",
    "broadcast_maximum": "ew", "broadcast_minimum": "ew",
    "elemwise_add": "ew", "elemwise_sub": "ew", "elemwise_mul": "ew",
    "elemwise_div": "ew",
    # elementwise math
    "tanh": "ew", "sigmoid": "ew", "relu": "ew", "exp": "ew", "log": "ew",
    "sqrt": "ew", "square": "ew", "abs": "ew", "negative": "ew",
    "clip": "ew",
    # rowwise NN ops: output row i is a function of input row i only
    "activation": ("row", (0,), None),
    "fully_connected": ("row", (0,), _fc_flatten),
    "flatten": ("row", (0,), None),
    "softmax": ("row", (0,), _softmax_axis_ok),
    "log_softmax": ("row", (0,), _softmax_axis_ok),
    "dot": ("row", (0,), _dot_rowwise),
}


def register_batch_safe(opname, rule):
    """Extension point: declare an op safe for batch-axis bucketing.
    ``rule`` is "ew" or ("row", (slots...), guard_or_None) — see the
    ``_BATCH_SAFE`` table comment for the row-independence contract the
    op must honor."""
    _BATCH_SAFE[opname] = rule


def _bound_config(opname, arg_template, kwargs):
    """The op's config as the body sees it: kwargs plus POSITIONAL
    literals bound to their parameter names through the op signature
    (``nd.softmax(x, None, 0)`` passes axis positionally — a guard that
    only saw kwargs would miss the row-mixing axis). None when binding
    fails: an unresolvable config must veto, not pass."""
    merged = dict(kwargs)
    if all(t[0] == "arr" for t in arg_template):
        return merged
    from ..ndarray.registry import get_op

    opdef = get_op(opname)
    if opdef is None:
        return None
    try:
        pos = [_ARR if t[0] == "arr" else t[1] for t in arg_template]
        bound = opdef.signature().bind_partial(*pos)
    except TypeError:
        return None
    for name, val in bound.arguments.items():
        if val is not _ARR and name not in merged:
            merged[name] = val
    return merged


_ARR = object()  # placeholder for array operands during bind_partial


def plan_bucketing(opname, datas, arg_template, kwargs):
    """(padded_batch, true_batch, pad_slots) when this dispatch should
    run through a bucket executable, else None. ``pad_slots`` indexes
    ``datas``. Conservative: any operand layout or config the rule
    cannot prove row-independent vetoes the plan."""
    spec = bucket_spec()
    if spec is None or not datas:
        return None
    rule = _BATCH_SAFE.get(opname)
    if rule is None:
        return None
    if rule == "ew":
        ndim = max(d.ndim for d in datas)
        if ndim == 0:
            return None
        batch = max((d.shape[0] for d in datas if d.ndim == ndim),
                    default=0)
        if batch <= 1:
            return None
        slots = []
        for i, d in enumerate(datas):
            if d.ndim == ndim and d.shape[0] == batch:
                slots.append(i)
            elif d.ndim == ndim and d.shape[0] != 1:
                return None  # ragged axis-0 mix: not a broadcast layout
        if not slots:
            return None
    else:
        _, arg_slots, guard = rule
        slots = [s for s in arg_slots if s < len(datas)]
        if not slots:
            return None
        # rank >= 2: on a 1-D operand axis 0 is the data/contraction
        # axis (dot lhs, softmax vector), never a batch to pad
        if any(datas[s].ndim < 2 for s in slots):
            return None
        if guard is not None:
            config = _bound_config(opname, arg_template, kwargs)
            if config is None:
                return None
            try:
                if not guard(config, datas):
                    return None
            except Exception:
                return None
        batch = datas[slots[0]].shape[0]
        if batch <= 1:
            return None
        if any(datas[s].shape[0] != batch for s in slots):
            return None
    padded = bucket_size(batch, spec)
    if padded == batch:
        return None
    return padded, batch, tuple(slots)


def pad_batch(data, padded):
    """Zero-pad axis 0 up to the bucket boundary (zeros: safe for every
    whitelisted op — padded rows may compute inf/nan garbage, but those
    rows are sliced off before anyone reads them)."""
    import jax.numpy as jnp

    n = data.shape[0]
    if n == padded:
        return data
    return jnp.concatenate(
        [data, jnp.zeros((padded - n,) + data.shape[1:], data.dtype)], 0)


def slice_batch(data, padded, true):
    """Undo ``pad_batch`` on an output whose axis 0 is the padded
    batch."""
    if data.ndim and data.shape[0] == padded:
        return data[:true]
    return data


def note_bucketed(padded, true):
    _bump("bucketed_calls")
    _bump("padded_rows", padded - true)
    _bump("true_rows", true)
