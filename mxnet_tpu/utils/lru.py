"""Bounded LRU of compiled executables, with dispatch counters.

One implementation shared by the two executable caches on the hot
paths: the compiled eager-dispatch cache (ndarray/registry.py, PR 1)
and the fused train-step cache (gluon/fused_step.py, PR 2). Thread-safe;
`stats()` is the counter surface profiler.*_counters() exposes.
"""
from __future__ import annotations

from collections import OrderedDict

from . import locks as _locks

__all__ = ["CountedLRUCache"]


class CountedLRUCache:
    def __init__(self, maxsize):
        self.maxsize = maxsize
        self._d = OrderedDict()
        # guards: _d, hits, misses, evictions, bypasses, fallbacks
        self._lock = _locks.RankedLock("utils.lru")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bypasses = 0   # dispatches that could not use the cache
        self.fallbacks = 0  # cached executable failed; caller went eager

    def lookup(self, key):
        with self._lock:
            entry = self._d.get(key)
            if entry is None:
                self.misses += 1
            else:
                self._d.move_to_end(key)
                self.hits += 1
            return entry

    def note_hit(self):
        """Hit served from a caller-side fast path (the full key was
        neither rebuilt nor hashed)."""
        with self._lock:
            self.hits += 1

    def note_bypass(self):
        with self._lock:
            self.bypasses += 1

    def note_fallback(self):
        with self._lock:
            self.fallbacks += 1

    def insert(self, key, entry):
        with self._lock:
            self._d[key] = entry
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1

    def remove(self, key):
        with self._lock:
            self._d.pop(key, None)

    def clear(self):
        with self._lock:
            self._d.clear()
            self.hits = self.misses = self.evictions = 0
            self.bypasses = self.fallbacks = 0

    def stats(self):
        with self._lock:
            return {"size": len(self._d), "maxsize": self.maxsize,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions, "bypasses": self.bypasses,
                    "fallbacks": self.fallbacks}
