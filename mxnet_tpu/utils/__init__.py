"""Misc utilities (download, env knobs).

Reference: python/mxnet/gluon/utils.py helpers + env-var config surface
(docs env_var.md — SURVEY Appendix B). TPU build keeps MXNET_* names where
semantics survive.
"""
from __future__ import annotations

import os

__all__ = ["getenv", "split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def getenv(name, default=None):
    """Read an MXNET_* knob (reference: dmlc::GetEnv use sites)."""
    return os.environ.get(name, default)


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Reference: gluon/utils.py split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Reference: gluon/utils.py split_and_load."""
    from .. import ndarray as nd
    from ..ndarray import NDArray

    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Reference: gluon/utils.py clip_global_norm."""
    import math

    from .. import ndarray as nd

    total = 0.0
    for arr in arrays:
        n = nd.norm(arr).asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        import warnings

        warnings.warn("nan or inf is detected.")
        return total
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._data = (arr * scale).data
    return total


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):  # pragma: no cover - zero-egress environment
    """Reference: gluon/utils.py download. This environment has no egress;
    raises unless the file already exists locally."""
    import os

    fname = path or url.split("/")[-1]
    if os.path.isdir(fname):
        fname = os.path.join(fname, url.split("/")[-1])
    if os.path.exists(fname) and not overwrite:
        return fname
    raise RuntimeError(f"download of {url} unavailable (no network egress); "
                       f"place the file at {fname} manually")
