"""Ranked locks: one declared lock order + a runtime deadlock witness.

Every lock in ``mxnet_tpu/`` is created through the factories here
(:func:`RankedLock` / :func:`RankedRLock` / :func:`RankedCondition`)
and carries a **name** and a **rank** from the single registry below
(:data:`LOCK_RANKS`, lower = outer = acquired first). graft_lint
L1101 makes raw ``threading.Lock()`` construction outside this module
a lint error, so the registry cannot rot.

``MXNET_LOCK_CHECK`` selects the mode **at lock construction**:

- ``0`` (default): the factories return the raw ``threading`` object
  — one env read at import, then literal passthrough; production pays
  nothing (bench-gated by ``BENCH_LOCKCHECK_r22.json``).
- ``warn`` / ``error``: the factories return checked wrappers and the
  witness runs on every acquire. The tier-1 conftest exports ``warn``
  before importing the package, so **every test doubles as a
  lock-discipline test**; ``warn``→``error`` can be flipped at runtime
  (:func:`set_check_mode`) — checked locks consult the live mode when
  a violation fires.

The witness is lockdep-style, two layers:

1. **Held-stack rank check** — a thread-local stack of currently-held
   locks; acquiring a lock whose rank is not strictly greater than the
   innermost held lock's is an ``out_of_rank`` violation, reported at
   the acquire site *before* the acquire (so ``error`` mode raises
   :class:`LockOrderError` instead of deadlocking). Re-entry on a held
   :func:`RankedRLock` is exempt.
2. **Acquisition-order graph** — a process-wide edge set
   (``A -> B`` recorded when B is acquired while A is held) with
   incremental cycle detection on every *new* edge, so an AB/BA
   *potential* deadlock is reported even when the interleaving never
   actually deadlocks (the classic lockdep move: one clean run of each
   path suffices to prove the hazard).

Violations surface three ways: the bounded :func:`violations` list
(what the conftest gate and :func:`capture_violations` read), the
``lock_check`` counter family in the r18 MetricsRegistry (Prometheus
``mxnet_lock_check_*`` + ``profiler.lock_check_counters()``), and a
telemetry instant event carrying both lock names when tracing is on.

See docs/CONCURRENCY.md for the rank table rationale, the
``# guards:`` annotation syntax (enforced by L1102), and how to add a
new lock.
"""
from __future__ import annotations

import logging
import threading
from contextlib import contextmanager

from .. import env

__all__ = [
    "LOCK_RANKS", "LockOrderError",
    "RankedLock", "RankedRLock", "RankedCondition",
    "check_mode", "set_check_mode",
    "violations", "clear_violations", "capture_violations", "exempt",
    "held_locks", "order_graph", "reset_order_graph",
    "lock_check_counters",
]

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# The one declared lock order (lower rank = outer = acquired first).
# Adding a lock means adding a row HERE, choosing its place in the
# global order from the call graph — see docs/CONCURRENCY.md.
# ---------------------------------------------------------------------------

LOCK_RANKS = {
    # engine band: outermost. engine.waiters is the r11 _reserve/_release
    # drain-protocol lock (_lock + _cond share it); nothing may be held
    # when it is taken, because it is acquired on every op push/wait.
    "engine.waiters": 0,
    "engine.singleton": 5,      # get()/fork re-init guard; never nests
    # serving control plane (outer -> inner along the request path)
    "serving.fleet": 8,         # FleetRouter replica table + hash ring
    "repository": 10,           # ModelRepository registration dict
    "repository.model": 20,     # per-_Model deploy/promote/rollback
    "batcher": 30,              # DynamicBatcher _closed flag
    "batcher.queue": 35,        # per-SLO-class lane condition
    "serving.session": 40,      # InferenceSession AOT-entry tables
    "serving.store": 50,        # SessionStateStore slots + page pool
    "serving.metrics": 60,      # ServingMetrics counters/histograms
    # autotune tier: consulted from graph optimization (which may run
    # under serving.session) and salt resolution; nothing but telemetry
    # counters is ever acquired under these
    "autotune.registry": 66,    # DecisionPoint table
    "autotune.records": 68,     # TuningRecord cache + trial overrides
    # artifact tier (session/store call down into it)
    "artifact.salts": 70,       # salt-provider registry
    "artifact.remote.breakers": 72,  # per-URL breaker table
    "artifact.server.store": 74,     # ArtifactCacheServer object store
    "artifact.bundle.protected": 75,  # live-bundle fingerprint pins
    "kernels.serving_fused": 76,     # pad/slice jit caches
    # leaf utilities: callable from under any of the above
    "resilience.faults": 78,    # fault-injection plan + fire counts
    "resilience.breaker": 80,   # per-CircuitBreaker state
    "utils.lru": 82,            # CountedLRUCache (compile caches)
    "ndarray.save_pool": 84,    # save() writer-pool keepalive
    "profiler": 86,             # host-side aggregate/event tables
    # telemetry: innermost — counters are bumped under everything
    "telemetry.boot": 88,       # one-shot probe bootstrap
    "telemetry.registry": 90,   # MetricsRegistry family tables
    "telemetry.counters": 95,   # every CounterFamily instance
}


class LockOrderError(RuntimeError):
    """Raised (``MXNET_LOCK_CHECK=error``) at a violating acquire site,
    *before* the acquire — the lock is NOT taken when this raises."""


# -- mode ------------------------------------------------------------------

def _read_mode():
    v = (env.get_str("MXNET_LOCK_CHECK", "0") or "0").strip().lower()
    if v in ("", "0", "off", "false"):
        return "0"
    if v in ("warn", "1", "warning"):
        return "warn"
    if v == "error":
        return "error"
    log.warning("MXNET_LOCK_CHECK=%r not recognized; using 'warn'", v)
    return "warn"


_MODE = _read_mode()  # the one env read; level 0 never pays again


def check_mode():
    """Current witness mode: ``"0"``, ``"warn"`` or ``"error"``."""
    return _MODE


def set_check_mode(mode):
    """Override the witness mode at runtime (tests, benchmarks).

    Affects (a) which flavor the factories return from now on and
    (b) whether already-constructed *checked* locks raise or count —
    it cannot retrofit checking onto raw locks built at level 0.
    Returns the previous mode."""
    global _MODE
    if mode not in ("0", "warn", "error"):
        raise ValueError(f"bad lock-check mode {mode!r}")
    prev, _MODE = _MODE, mode
    return prev


# -- witness state ---------------------------------------------------------

class _TLS(threading.local):
    def __init__(self):
        self.stack = []       # [lock, entry_count] innermost last
        self.reporting = False  # re-entrancy guard for the witness itself


_tls = _TLS()

# The witness's own locks stay raw on purpose: ranking them would make
# the witness recurse into itself.
_GRAPH_LOCK = threading.Lock()  # graft-lint: allow(L1101) — witness internals
_EDGES = {}        # name -> set(name): B acquired while A held
_SEEN_EDGES = set()  # (a, b) dedupe; unlocked membership fast path
_VIOLATIONS = []   # bounded; conftest gate + capture_violations() read it
_MAX_VIOLATIONS = 256
_FAMILY = None     # lazy lock_check CounterFamily

_COUNTER_ZEROS = {"out_of_rank": 0, "cycles": 0, "edges": 0,
                  "self_deadlock": 0, "violations_dropped": 0}


def _bump(key, n=1):
    """Bump the lock_check counter family without re-entering the
    witness (the family's own lock is ranked)."""
    global _FAMILY
    was = _tls.reporting
    _tls.reporting = True
    try:
        if _FAMILY is None:
            from ..telemetry.metrics import counter_family
            _FAMILY = counter_family("lock_check", _COUNTER_ZEROS)
        _FAMILY.add(key, n)
    finally:
        _tls.reporting = was


def lock_check_counters():
    """Snapshot of the ``lock_check`` family (zeros before first use)."""
    if _FAMILY is None:
        return dict(_COUNTER_ZEROS)
    return _FAMILY.snapshot()


def _report(kind, message, acquiring=None):
    """Record one violation: bounded list + counter + log + telemetry
    instant; raises LockOrderError in ``error`` mode (before acquire)."""
    if _tls.reporting:
        return
    _tls.reporting = True
    try:
        held = [(lk.name, lk.rank) for lk, _ in _tls.stack]
        rec = {"kind": kind, "message": message,
               "thread": threading.current_thread().name,
               "held": held,
               "acquiring": None if acquiring is None else acquiring.name}
        with _GRAPH_LOCK:
            dropped = len(_VIOLATIONS) >= _MAX_VIOLATIONS
            if not dropped:
                _VIOLATIONS.append(rec)
        _bump("cycles" if kind == "cycle" else kind)
        if dropped:
            _bump("violations_dropped")
        log.warning("lock_check[%s]: %s (thread=%s held=%s)",
                    kind, message, rec["thread"], held)
        try:
            from ..telemetry import tracer
            tracer.instant("lock_check." + kind, cat="lock",
                           message=message,
                           held=",".join(n for n, _ in held),
                           acquiring=rec["acquiring"] or "")
        except Exception:  # graft-lint: allow(L501) — witness must not throw
            pass
    finally:
        _tls.reporting = False
    if _MODE == "error":
        raise LockOrderError(message)


def _find_path(src, dst):
    """DFS over the edge graph: a path src -> ... -> dst, or None."""
    stack, seen = [(src, (src,))], {src}
    while stack:
        node, path = stack.pop()
        for nxt in _EDGES.get(node, ()):
            if nxt == dst:
                return path + (nxt,)
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _note_edge(outer, inner):
    """Record outer->inner in the acquisition-order graph; on a NEW
    edge, run incremental cycle detection (lockdep-style)."""
    key = (outer.name, inner.name)
    if key in _SEEN_EDGES:  # benign unlocked fast path; recheck below
        return
    with _GRAPH_LOCK:
        if key in _SEEN_EDGES:
            return
        _SEEN_EDGES.add(key)
        _EDGES.setdefault(outer.name, set()).add(inner.name)
        # cycle through the new edge <=> a path inner -> ... -> outer
        path = _find_path(inner.name, outer.name)
    _bump("edges")
    if path is not None:
        cycle = " -> ".join((outer.name,) + path)
        _report(
            "cycle",
            f"lock-order cycle (potential deadlock): {cycle}; "
            f"edge {outer.name}->{inner.name} closes it",
            acquiring=inner)


def _check_acquire(lock):
    """Pre-acquire witness: rank check + edge recording. Returns True
    when this is a re-entrant acquire of an already-held RLock."""
    st = _tls.stack
    for ent in st:
        if ent[0] is lock:
            if lock._reentrant:
                return True
            _report(
                "self_deadlock",
                f"re-acquiring non-reentrant lock '{lock.name}' "
                f"already held by this thread (certain deadlock)",
                acquiring=lock)
            return False
    if st and not _tls.reporting:
        top = st[-1][0]
        if lock.rank <= top.rank:
            _report(
                "out_of_rank",
                f"acquiring '{lock.name}' (rank {lock.rank}) while "
                f"holding '{top.name}' (rank {top.rank}); declared "
                f"order is ascending — see LOCK_RANKS in "
                f"mxnet_tpu/utils/locks.py",
                acquiring=lock)
        _note_edge(top, lock)
    return False


def _push(lock):
    _tls.stack.append([lock, 1])


def _pop(lock):
    st = _tls.stack
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] is lock:
            st[i][1] -= 1
            if st[i][1] == 0:
                del st[i]
            return
    # released on a different thread than acquired (legal for Lock used
    # as a gate); nothing to pop here.


# -- checked wrappers ------------------------------------------------------

class _CheckedLock:
    """Witness wrapper over threading.Lock/RLock. Context-manager and
    acquire/release compatible; the raw primitive is ``_raw``."""

    __slots__ = ("_raw", "name", "rank", "_reentrant")

    def __init__(self, raw, name, rank, reentrant):
        self._raw = raw
        self.name = name
        self.rank = rank
        self._reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        reentry = _check_acquire(self)
        got = self._raw.acquire(blocking, timeout)
        if got:
            if reentry:
                for ent in _tls.stack:
                    if ent[0] is self:
                        ent[1] += 1
                        break
            else:
                _push(self)
        return got

    def release(self):
        self._raw.release()
        _pop(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._raw.locked()

    def held_by_me(self):
        """Whether the calling thread holds this lock (witness data)."""
        return any(ent[0] is self for ent in _tls.stack)

    def __repr__(self):
        kind = "RankedRLock" if self._reentrant else "RankedLock"
        return f"<{kind} {self.name!r} rank={self.rank}>"


class _CheckedCondition:
    """Condition over a checked lock: enter/exit run the witness; the
    internal threading.Condition operates on the RAW lock, so wait()
    brackets the raw release/reacquire by popping and re-pushing the
    held-stack entry (the wakeup reacquire recreates exactly the
    pre-wait held state, already vetted at the original acquire)."""

    __slots__ = ("_clock", "_cond")

    def __init__(self, checked_lock):
        self._clock = checked_lock
        self._cond = threading.Condition(checked_lock._raw)

    @property
    def name(self):
        return self._clock.name

    @property
    def rank(self):
        return self._clock.rank

    @property
    def lock(self):
        """The checked lock this condition synchronizes on."""
        return self._clock

    def acquire(self, blocking=True, timeout=-1):
        return self._clock.acquire(blocking, timeout)

    def release(self):
        self._clock.release()

    def __enter__(self):
        self._clock.acquire()
        return self

    def __exit__(self, *exc):
        self._clock.release()

    def wait(self, timeout=None):
        st = _tls.stack
        ent = None
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] is self._clock:
                ent = st.pop(i)
                break
        try:
            return self._cond.wait(timeout)
        finally:
            if ent is not None:
                st.append(ent)

    def wait_for(self, predicate, timeout=None):
        import time as _time
        result = predicate()
        if result:
            return result
        endtime = None if timeout is None \
            else _time.monotonic() + timeout
        while not result:
            if endtime is not None:
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f"<RankedCondition {self.name!r} rank={self.rank}>"


# -- factories -------------------------------------------------------------

def _rank_of(name, rank):
    if rank is not None:
        return rank
    try:
        return LOCK_RANKS[name]
    except KeyError:
        raise KeyError(
            f"lock name {name!r} is not in LOCK_RANKS; declare it in "
            f"mxnet_tpu/utils/locks.py (see docs/CONCURRENCY.md)"
        ) from None


def RankedLock(name, rank=None):
    """A named, ranked mutex. Level 0: a raw ``threading.Lock``."""
    if _MODE == "0":
        return threading.Lock()  # graft-lint: allow(L1101) — passthrough
    return _CheckedLock(threading.Lock(), name, _rank_of(name, rank),
                        reentrant=False)


def RankedRLock(name, rank=None):
    """A named, ranked re-entrant mutex. Level 0: a raw RLock."""
    if _MODE == "0":
        return threading.RLock()  # graft-lint: allow(L1101) — passthrough
    return _CheckedLock(threading.RLock(), name, _rank_of(name, rank),
                        reentrant=True)


def RankedCondition(name=None, lock=None, rank=None):
    """A condition variable over a ranked lock.

    ``lock=`` shares an existing :func:`RankedLock`/:func:`RankedRLock`
    (the engine ``_cond = Condition(self._lock)`` pattern — same lock,
    same rank, ONE held-stack identity); otherwise a new RankedRLock
    ``name`` is created underneath, mirroring ``threading.Condition()``
    defaulting to an RLock."""
    if _MODE == "0":
        if isinstance(lock, _CheckedLock):  # mixed modes (tests)
            lock = lock._raw
        return threading.Condition(lock)  # graft-lint: allow(L1101)
    if lock is None:
        if name is None:
            raise ValueError("RankedCondition needs name= or lock=")
        lock = _CheckedLock(threading.RLock(), name,
                            _rank_of(name, rank), reentrant=True)
    elif not isinstance(lock, _CheckedLock):
        raise TypeError(
            "RankedCondition(lock=...) wants a RankedLock/RankedRLock "
            f"(got {type(lock).__name__}); raw locks are invisible to "
            "the witness")
    return _CheckedCondition(lock)


# -- introspection / test support -----------------------------------------

@contextmanager
def exempt(reason):
    """Suppress the witness for acquisitions inside the block (locks
    are still tracked on the held stack, so release stays balanced).

    For acquisition contexts whose interleaving is arbitrary *by
    construction* and provably deadlock-free: a GC finalizer
    (``__del__`` → ``close()``) runs at whatever allocation point the
    interpreter picked, under whatever locks the interrupted thread
    holds — but the locks it takes belong to an unreachable instance
    no live thread can hold, so the inverted-looking order it records
    can never complete a real deadlock. Every call site must pass a
    ``reason`` string (it is the audit trail)."""
    if not reason:
        raise ValueError("locks.exempt() requires a reason")
    was = _tls.reporting
    _tls.reporting = True
    try:
        yield
    finally:
        _tls.reporting = was


def held_locks():
    """``[(name, rank), ...]`` held by the calling thread, outer first."""
    return [(lk.name, lk.rank) for lk, _ in _tls.stack]


def violations():
    """Snapshot of recorded violations (bounded at 256)."""
    with _GRAPH_LOCK:
        return list(_VIOLATIONS)


def clear_violations():
    with _GRAPH_LOCK:
        _VIOLATIONS.clear()


@contextmanager
def capture_violations():
    """Collect violations recorded inside the block into the yielded
    list and REMOVE them from the global record — witness tests assert
    on them without tripping the tier-1 conftest zero-violation gate."""
    with _GRAPH_LOCK:
        start = len(_VIOLATIONS)
    captured = []
    try:
        yield captured
    finally:
        with _GRAPH_LOCK:
            captured.extend(_VIOLATIONS[start:])
            del _VIOLATIONS[start:]


def order_graph():
    """Copy of the acquisition-order graph: ``{name: set(names)}``."""
    with _GRAPH_LOCK:
        return {k: set(v) for k, v in _EDGES.items()}


def reset_order_graph():
    """Forget observed edges (witness tests build synthetic orders)."""
    with _GRAPH_LOCK:
        _EDGES.clear()
        _SEEN_EDGES.clear()
