"""Training-loop callbacks for ``Module.fit`` / ``model.fit``.

Reference surface: python/mxnet/callback.py (Speedometer, do_checkpoint,
module_checkpoint, log_train_metric, LogValidationMetricsCallback,
ProgressBar). The call contracts are fixed by the fit loop — epoch-end
callbacks receive ``(epoch, symbol, arg_params, aux_params)``, batch-end
callbacks a ``BatchEndParam`` namedtuple — but the machinery here is this
package's own: one periodic-trigger helper shared by everything periodic,
metric formatting in one place, and wall-clock via ``perf_counter``.
"""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "log_train_metric", "LogValidationMetricsCallback", "ProgressBar"]


def _fires(index, period):
    """True on every `period`-th 1-based tick of a 0-based index."""
    return (index + 1) % period == 0


def _metric_pairs(metric):
    """(name, value) pairs of an EvalMetric, or () when there is none."""
    return tuple(metric.get_name_value()) if metric is not None else ()


def _fmt_pairs(pairs):
    return "\t".join(f"{n}={v:f}" for n, v in pairs)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback saving `mod` every `period` epochs
    (reference: callback.py module_checkpoint)."""
    period = max(1, int(period))

    def _callback(epoch, sym=None, arg=None, aux=None):
        if _fires(epoch, period):
            mod.save_checkpoint(prefix, epoch + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback writing `prefix`-symbol.json / -NNNN.params
    every `period` epochs (reference: callback.py do_checkpoint)."""
    from .model import save_checkpoint

    period = max(1, int(period))

    def _callback(epoch, sym, arg, aux):
        if _fires(epoch, period):
            save_checkpoint(prefix, epoch + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the running training metric every
    `period` batches (reference: callback.py log_train_metric)."""

    def _callback(param):
        if param.nbatch % period != 0:
            return
        pairs = _metric_pairs(param.eval_metric)
        if not pairs:
            return
        logging.info("Iter[%d] Batch[%d] %s", param.epoch, param.nbatch,
                     _fmt_pairs((f"Train-{n}", v) for n, v in pairs))
        if auto_reset:
            param.eval_metric.reset()

    return _callback


class Speedometer:
    """Batch-end callback printing samples/sec (and optionally the
    running metric) every `frequent` batches (reference: callback.py
    Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._mark = None       # perf_counter at the last report/epoch start
        self._prev_batch = -1

    def __call__(self, param):
        if param.nbatch < self._prev_batch:
            self._mark = None   # new epoch: timing window restarts
        self._prev_batch = param.nbatch
        if self._mark is None:
            self._mark = time.perf_counter()
            return
        if param.nbatch % self.frequent != 0:
            return
        elapsed = time.perf_counter() - self._mark
        speed = (self.frequent * self.batch_size / elapsed) if elapsed \
            else float("inf")
        pairs = _metric_pairs(param.eval_metric)
        if pairs:
            if self.auto_reset:
                param.eval_metric.reset()
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s",
                         param.epoch, param.nbatch, speed, _fmt_pairs(pairs))
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, param.nbatch, speed)
        self._mark = time.perf_counter()


class LogValidationMetricsCallback:
    """Eval-end callback logging every validation metric
    (reference: callback.py LogValidationMetricsCallback)."""

    def __call__(self, param):
        pairs = _metric_pairs(param.eval_metric)
        for name, value in pairs:
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)


class ProgressBar:
    """Batch-end callback rendering a text progress bar
    (reference: callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.total = max(1, int(total))
        self.length = int(length)

    def __call__(self, param):
        frac = min(max(param.nbatch / float(self.total), 0.0), 1.0)
        done = int(round(self.length * frac))
        bar = "=" * done + "-" * (self.length - done)
        logging.info("[%s] %d%%\r", bar, int(round(100 * frac)))
