"""DataIter protocol + NDArrayIter and friends.

TPU-native equivalent of python/mxnet/io/io.py (reference: DataIter/
DataBatch protocol :180-790, NDArrayIter, ResizeIter, PrefetchingIter) and
the C++ MNIST/CSV iterators (reference: src/io/iter_mnist.cc,
src/io/iter_csv.cc). Host-side batching feeds the device asynchronously —
`next()` returns NDArrays whose device transfer overlaps compute thanks to
XLA async dispatch; PrefetchingIter adds a background thread double-buffer
like the reference's dmlc::ThreadedIter (src/io/iter_prefetcher.h:142).
"""
from __future__ import annotations

from collections import namedtuple

import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray import NDArray
from ..pipeline.device_feed import DeviceFeed as _DeviceFeedBase

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Reference: io.py DataDesc (name/shape/dtype/layout)."""

    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """Reference: io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Reference: io.py DataIter base."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy array) (reference: io.py _init_data)."""
    if data is None:
        if not allow_empty:
            raise ValueError(f"{default_name} must be set")
        return []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError(f"{default_name} cannot be empty")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, onp.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py NDArrayIter:180)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = onp.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        self.cursor = -batch_size
        self._cache = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _batchify(self, arrays):
        if self.cursor + self.batch_size <= self.num_data:
            sel = self.idx[self.cursor:self.cursor + self.batch_size]
        elif self.last_batch_handle == "pad":
            pad = self.batch_size - (self.num_data - self.cursor)
            sel = onp.concatenate([self.idx[self.cursor:], self.idx[:pad]])
        else:
            sel = self.idx[self.cursor:]
        return [nd.array(v[sel], dtype=v.dtype) for _, v in arrays]

    def getdata(self):
        return self._batchify(self.data)

    def getlabel(self):
        return self._batchify(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize the epoch length of an iterator (reference: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Engine-scheduled double buffering (reference: io.py PrefetchingIter,
    C++ analog src/io/iter_prefetcher.h:142).

    Each sub-iterator owns an engine variable; fetching its next batch is
    an op pushed to the engine's IO lane with that variable mutable —
    exactly the reference's prefetcher op (iter_prefetcher.h pushes to
    the engine's IO thread pool). The fetch of batch k+1 overlaps the
    consumption of batch k; ``MXNET_ENGINE_TYPE=NaiveEngine`` makes every
    fetch synchronous at push (observable serialization)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        from .. import engine

        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = iters[0].batch_size
        self.current_batch = None
        self.next_batch = [None for _ in iters]
        self._engine = engine.get()
        self._vars = [self._engine.new_variable() for _ in iters]
        self._push_fetches()

    def _fresh_vars(self):
        """Poison is permanent on a var — after an error (or at reset)
        the pipeline continues on fresh ones."""
        self._vars = [self._engine.new_variable() for _ in self.iters]

    def _push_fetches(self):
        """Schedule one fetch op per sub-iterator on the IO lane."""
        from .. import engine

        for i in range(len(self.iters)):
            def fetch(i=i):
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None

            self._engine.push(fetch, mutable_vars=(self._vars[i],),
                              lane=engine.LANE_IO)

    def _wait_fetches(self):
        for v in self._vars:
            self._engine.wait_for_var(v)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for v in self._vars:  # drain in-flight fetches before rewinding;
            try:              # stale errors die with the abandoned epoch
                self._engine.wait_for_var(v)
            except BaseException:  # graft-lint: allow(L501)
                pass
        self._fresh_vars()
        for i in self.iters:
            i.reset()
        self._push_fetches()

    def iter_next(self):
        self._wait_fetches()
        if self.next_batch[0] is None:
            return False
        self.current_batch = DataBatch(
            sum([b.data for b in self.next_batch], []),
            sum([(b.label or []) for b in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index)
        self._push_fetches()  # overlap the NEXT fetch with consumption
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class MNISTIter(NDArrayIter):
    """MNIST iterator (reference: src/io/iter_mnist.cc). Reads the idx-ubyte
    files the reference reads; synthesizes data when files are absent
    (input_shape-shaped random digits) so smoke tests run hermetically."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, seed=0, silent=False,
                 num_parts=1, part_index=0, input_shape=None, **kwargs):
        import gzip
        import os
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic = struct.unpack(">HBB", f.read(4))
                ndim = magic[2]
                shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(shape)

        if os.path.exists(image) or os.path.exists(image + ".gz"):
            img_path = image if os.path.exists(image) else image + ".gz"
            lbl_path = label if os.path.exists(label) else label + ".gz"
            images = read_idx(img_path).astype(onp.float32) / 255.0
            labels = read_idx(lbl_path).astype(onp.float32)
        else:  # hermetic fallback
            rng = onp.random.RandomState(seed)
            n = 1024
            images = rng.rand(n, 28, 28).astype(onp.float32)
            labels = rng.randint(0, 10, (n,)).astype(onp.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1, 28, 28)
        if num_parts > 1:
            images = images[part_index::num_parts]
            labels = labels[part_index::num_parts]
        super().__init__(images, labels, batch_size=int(batch_size),
                         shuffle=bool(shuffle), last_batch_handle="discard")


def _parse_csv(path):
    """Parse a float CSV with the compiled multithreaded parser
    (native/textio.cc, the analog of src/io/iter_csv.cc's C++ parse);
    numpy.loadtxt only as the no-toolchain fallback."""
    from .._native import textlib

    if textlib is not None:
        h = textlib.csv_parse(str(path).encode())
        if not h:
            raise MXNetError(
                f"CSV parse failed: "
                f"{textlib.textio_last_error().decode()}")
        try:
            rows, cols = textlib.csv_rows(h), textlib.csv_cols(h)
            if rows * cols == 0:
                return onp.zeros((rows, cols), dtype=onp.float32)
            flat = onp.ctypeslib.as_array(
                textlib.csv_data(h), shape=(rows * cols,))
            return flat.reshape(rows, cols).copy()
        finally:
            textlib.csv_free(h)
    return onp.loadtxt(path, delimiter=",", dtype=onp.float32,
                       ndmin=2)


class CSVIter(NDArrayIter):
    """CSV iterator (reference: src/io/iter_csv.cc). Parsing is native
    C++ (GIL-free, line-chunk multithreaded) via native/textio.cc."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = _parse_csv(data_csv).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _parse_csv(label_csv).reshape(
                (-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard")


class DevicePrefetchIter(_DeviceFeedBase):
    """Host→device double buffering: a background thread pulls batches
    from the wrapped iterator and stages them onto the target device
    with an ASYNC jax.device_put, so the transfer of batch k+1 overlaps
    the compiled step consuming batch k (the missing half of the
    reference's prefetch story — iter_prefetcher.h overlaps decode with
    compute, PJRT async H2D overlaps the copy with the device step).

    Since round 11 this is a thin wrapper over the general
    ``mxnet_tpu.pipeline.DeviceFeed`` (one prefetch implementation, one
    set of counters); kept for the original (base, device, depth)
    signature. depth=2 keeps at most two staged batches in flight (one
    being consumed, one in transfer) — deeper queues only add HBM
    pressure."""

    def __init__(self, base, device=None, depth=2):
        super().__init__(base, depth=depth, device=device)
        self.base = base
