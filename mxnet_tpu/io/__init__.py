"""Data iterators (reference: python/mxnet/io/io.py + src/io/)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, DevicePrefetchIter, MNISTIter, CSVIter)
from .image_record import ImageRecordIter, ImageDetRecordIter, LibSVMIter

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "DevicePrefetchIter", "MNISTIter", "CSVIter",
           "ImageRecordIter", "ImageDetRecordIter", "LibSVMIter"]
