"""Data iterators (reference: python/mxnet/io/io.py)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MNISTIter, CSVIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter"]
