"""ImageRecordIter / ImageDetRecordIter / LibSVMIter.

TPU-native re-design of the reference's C++ input pipeline
(src/io/iter_image_recordio_2.cc: chunked record read → OMP-parallel JPEG
decode+augment → batch → PrefetcherIter double-buffer;
src/io/iter_libsvm.cc). Decode + crop + mirror run in the native library's
thread pool (native/recordio.cc, no GIL); normalization (mean/std/scale)
runs on-device in jnp so XLA fuses it with the first conv — host→HBM
transfer stays uint8, 4x smaller than shipping float32.
"""
from __future__ import annotations

import os
import struct

import numpy as onp

from .io import DataIter, DataBatch, DataDesc
from .. import recordio as rio


def _index_offsets(path_imgrec, path_imgidx=None):
    """Byte offset of every record (from .idx sidecar or a full scan)."""
    if path_imgidx and os.path.isfile(path_imgidx):
        offsets = []
        with open(path_imgidx) as f:
            for line in f:
                parts = line.strip().split("\t")
                if len(parts) >= 2:
                    offsets.append(int(parts[1]))
        if offsets:
            return offsets
    offsets = []
    from .. import _native
    if _native.lib is not None:
        import ctypes
        h = _native.lib.rio_open(path_imgrec.encode())
        if h:
            out = ctypes.POINTER(ctypes.c_ubyte)()
            while True:
                pos = _native.lib.rio_tell(h)
                n = _native.lib.rio_next(h, ctypes.byref(out))
                if n < 0:
                    break
                offsets.append(pos)
            _native.lib.rio_close(h)
            return offsets
    r = rio.MXRecordIO(path_imgrec, "r")
    while True:
        pos = r.tell()
        if r.read() is None:
            break
        offsets.append(pos)
    r.close()
    return offsets


def _decode_batch_python(blobs, H, W, resize_short, crops):
    """PIL fallback mirroring native decode_batch semantics."""
    from io import BytesIO
    from PIL import Image

    out = onp.zeros((len(blobs), H, W, 3), dtype=onp.uint8)
    for i, blob in enumerate(blobs):
        try:
            im = Image.open(BytesIO(blob)).convert("RGB")
        except Exception:
            continue
        sw, sh = im.size
        tw, th = sw, sh
        if resize_short > 0:
            if sh < sw:
                th, tw = resize_short, max(1, sw * resize_short // sh)
            else:
                tw, th = resize_short, max(1, sh * resize_short // sw)
        # proportional cover-scale up to the crop (same order as native)
        if tw < W:
            th = th * W // tw
            tw = W
        if th < H:
            tw = tw * H // th
            th = H
        if (tw, th) != (sw, sh):
            im = im.resize((tw, th), Image.BILINEAR)
            sw, sh = tw, th
        cy, cx, mirror = crops[i]
        if cy < 0:
            cy = (sh - H) // 2
        else:
            cy = cy * (sh - H) // 10000
        if cx < 0:
            cx = (sw - W) // 2
        else:
            cx = cx * (sw - W) // 10000
        cy = min(max(cy, 0), sh - H)
        cx = min(max(cx, 0), sw - W)
        arr = onp.asarray(im)[cy:cy + H, cx:cx + W]
        if mirror:
            arr = arr[:, ::-1]
        out[i] = arr
    return out


class ImageRecordIter(DataIter):
    """Reference: ImageRecordIter v2 (src/io/iter_image_recordio_2.cc:880,
    augmenters src/io/image_aug_default.cc). Supported params mirror the
    common reference surface: data_shape, batch_size, shuffle, resize
    (short edge), rand_crop, rand_mirror, mean/std per channel, scale,
    label_width, part_index/num_parts sharding, preprocess_threads,
    prefetch_buffer, round_batch."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, resize=-1, rand_crop=False,
                 rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0, label_width=1,
                 part_index=0, num_parts=1, preprocess_threads=4,
                 prefetch_buffer=2, round_batch=True, seed=0,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3 and data_shape[0] == 3, \
            "data_shape must be (3, H, W)"
        self.path_imgrec = path_imgrec
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.resize = resize
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.scale = scale
        self.round_batch = round_batch
        self.preprocess_threads = max(1, int(preprocess_threads))
        self.prefetch_buffer = max(1, int(prefetch_buffer))
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self._mean = onp.array([mean_r, mean_g, mean_b], dtype=onp.float32)
        self._std = onp.array([std_r, std_g, std_b], dtype=onp.float32)
        self._rng = onp.random.RandomState(seed)

        offsets = _index_offsets(path_imgrec, path_imgidx)
        # part_index/num_parts sharding (reference: distributed data split)
        offsets = offsets[part_index::num_parts]
        if not offsets:
            raise ValueError(f"no records found in {path_imgrec}")
        self._offsets = onp.array(offsets, dtype=onp.int64)
        self._fp = open(path_imgrec, "rb")
        self._order = onp.arange(len(self._offsets))
        # decode pipeline state: batch decodes are ENGINE ops on the IO
        # lane (reference iter_image_recordio_2.cc hands decoded batches
        # to the engine's IO workers). _file_var serializes record reads
        # on the shared fp; one var per prefetch slot orders producer
        # vs consumer on that slot.
        from .. import engine

        self._engine = engine.get()
        self._depth = self.prefetch_buffer
        self._slot_vars = []
        self._slots = [None] * self._depth
        self._nbatch = 0
        self._next_emit = 0
        self._next_push = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shp)]

    def _read_record(self, offset):
        self._fp.seek(offset)
        head = self._fp.read(8)
        magic, lrec = struct.unpack("<II", head)
        cflag, length = lrec >> 29, lrec & ((1 << 29) - 1)
        if cflag == 0:
            return self._fp.read(length)
        # split record: reassemble via the recordio module
        r = rio.MXRecordIO(self.path_imgrec, "r")
        r.fio.seek(offset)
        data = r.read()
        r.close()
        return data

    @staticmethod
    def _pad_idxs(idxs, epoch_order, bs):
        """Fill a short final batch by wrapping the epoch order (tiled, so
        shards smaller than one batch still fill up)."""
        pad = bs - len(idxs)
        if pad:
            reps = -(-pad // len(epoch_order))
            filler = onp.tile(epoch_order, reps)[:pad]
            idxs = onp.concatenate([idxs, filler])
        return idxs, pad

    def _make_label(self, lab):
        """Fixed-width label row from a record header (subclass hook)."""
        if lab.size < self.label_width:
            lab = onp.pad(lab, (0, self.label_width - lab.size))
        return lab[:self.label_width]

    def _augment_plan(self, bs):
        """Per-batch crop/mirror draws. Pulled OUT of the decode ops so
        augmentation RNG is consumed in epoch order no matter how the
        engine schedules the ops. cy/cx: -1 = center; else fraction of
        free space /10000."""
        crops = onp.full((bs, 3), -1, dtype=onp.int32)
        crops[:, 2] = 0
        if self.rand_crop:
            crops[:, 0] = self._rng.randint(0, 10001, bs)
            crops[:, 1] = self._rng.randint(0, 10001, bs)
        if self.rand_mirror:
            crops[:, 2] = self._rng.randint(0, 2, bs)
        return crops

    def _decode_job(self, idxs, pad, crops, slot):
        """One engine op: read records, decode+augment, fill the slot."""
        C, H, W = self.data_shape
        blobs, labels = [], []
        for i in idxs:
            rec = self._read_record(int(self._offsets[i]))
            header, blob = rio.unpack(rec)
            lab = onp.atleast_1d(
                onp.asarray(header.label, dtype=onp.float32))
            labels.append(self._make_label(lab))
            blobs.append(blob)
        batch_u8 = self._decode(blobs, H, W, crops)
        label = onp.stack(labels)
        if self.label_width == 1 and label.ndim == 2:
            label = label[:, 0]
        self._slots[slot] = (batch_u8, label, pad)

    def _push_decode(self):
        from .. import engine

        b = self._next_push
        idxs, pad, crops = self._plan[b]
        slot = b % self._depth
        self._engine.push(
            lambda idxs=idxs, pad=pad, crops=crops, slot=slot:
                self._decode_job(idxs, pad, crops, slot),
            mutable_vars=(self._file_var, self._slot_vars[slot]),
            lane=engine.LANE_IO)
        self._next_push += 1

    def _drain(self):
        """Wait out in-flight decode ops (errors from an abandoned epoch
        are dropped — reset starts fresh)."""
        for v in self._slot_vars:
            try:
                self._engine.wait_for_var(v)
            except BaseException:  # graft-lint: allow(L501)
                pass

    def _decode(self, blobs, H, W, crops):
        from .. import _native
        resize_short = self.resize if self.resize and self.resize > 0 else 0
        if _native.lib is not None:
            import ctypes
            blob = b"".join(blobs)
            offs = onp.zeros(len(blobs), dtype=onp.int64)
            lens = onp.zeros(len(blobs), dtype=onp.int64)
            o = 0
            for i, b_ in enumerate(blobs):
                offs[i] = o
                lens[i] = len(b_)
                o += len(b_)
            out = onp.zeros((len(blobs), H, W, 3), dtype=onp.uint8)
            nat_crops = onp.ascontiguousarray(crops, dtype=onp.int32)
            cbuf = (ctypes.c_ubyte * len(blob)).from_buffer_copy(blob)
            _native.lib.decode_batch(
                ctypes.cast(cbuf, ctypes.POINTER(ctypes.c_ubyte)),
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(blobs), H, W, resize_short,
                nat_crops.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                self.preprocess_threads,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)))
            return out
        pcrops = [tuple(int(v) for v in crops[i]) for i in range(len(blobs))]
        return _decode_batch_python(blobs, H, W, resize_short, pcrops)

    def reset(self):
        self._drain()
        # FRESH vars each epoch: a decode error poisons its vars, and
        # poison has no un-poison — reusing the vars would make every
        # later epoch re-raise the stale error
        self._file_var = self._engine.new_variable()
        self._slot_vars = [self._engine.new_variable()
                           for _ in range(self._depth)]
        order = self._order.copy()
        if self.shuffle:
            self._rng.shuffle(order)
        bs = self.batch_size
        n = len(order)
        self._nbatch = (n + bs - 1) // bs if self.round_batch else n // bs
        # the epoch plan (batch indices + augmentation draws) is built
        # up front, in order; the engine ops only do IO + decode
        self._plan = []
        for b in range(self._nbatch):
            idxs, pad = self._pad_idxs(order[b * bs:(b + 1) * bs], order, bs)
            self._plan.append((idxs, pad, self._augment_plan(bs)))
        self._slots = [None] * self._depth
        self._next_emit = 0
        self._next_push = 0
        while self._next_push < min(self._depth, self._nbatch):
            self._push_decode()

    def next(self):
        from .. import nd

        if self._next_emit >= self._nbatch:
            raise StopIteration
        slot = self._next_emit % self._depth
        self._engine.wait_for_var(self._slot_vars[slot])  # re-raises errors
        batch_u8, label, pad = self._slots[slot]
        self._slots[slot] = None
        self._next_emit += 1
        if self._next_push < self._nbatch:
            self._push_decode()  # refill the slot window
        # device-side normalize: uint8 HWC → float CHW, (x-mean)/std*scale;
        # XLA fuses this into the consumer
        x = nd.array(batch_u8)
        x = (x.astype("float32") - nd.array(self._mean)) / \
            nd.array(self._std) * self.scale
        x = x.transpose((0, 3, 1, 2))
        if self.dtype != "float32":
            x = x.astype(self.dtype)
        return DataBatch(data=[x], label=[nd.array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ImageDetRecordIter(ImageRecordIter):
    """Detection variant (reference: src/io/iter_image_det_recordio.cc):
    labels are variable-length [header_width, obj_width, id, xmin, ymin,
    xmax, ymax, ...] padded with -1 to label_pad_width."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_pad_width=35, label_pad_value=-1.0, **kwargs):
        self._pad_value = label_pad_value
        kwargs.setdefault("label_width", label_pad_width)
        # geometric augmentation would have to transform the boxes too;
        # like before, the det iterator serves center-crop, no-mirror
        kwargs["rand_crop"] = False
        kwargs["rand_mirror"] = False
        super().__init__(path_imgrec, data_shape, batch_size, **kwargs)

    def _make_label(self, lab):
        # pad with label_pad_value (not 0 — boxes use -1 sentinel rows)
        out = onp.full(self.label_width, self._pad_value, dtype=onp.float32)
        out[:min(lab.size, self.label_width)] = lab[:self.label_width]
        return out


class LibSVMIter(DataIter):
    """Sparse text format iterator (reference: src/io/iter_libsvm.cc).
    Yields CSR data batches."""

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, round_batch=True, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape) if hasattr(data_shape, "__len__") \
            else (data_shape,)
        ncol = self.data_shape[-1]
        # labels come from the first token of each data line unless a
        # separate label file is given (reference: iter_libsvm.cc
        # label_libsvm param)
        inline_labels = label_libsvm is None
        parsed = self._parse_native(data_libsvm, inline_labels)
        if parsed is not None:
            values, indices, indptr, labels = parsed
        else:
            indptr, indices, values, labels = [0], [], [], []
            with open(data_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    feats = parts
                    if inline_labels:
                        labels.append(float(parts[0]))
                        feats = parts[1:]
                    for tok in feats:
                        k, v = tok.split(":")
                        indices.append(int(k))
                        values.append(float(v))
                    indptr.append(len(indices))
        if not inline_labels:
            with open(label_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        labels.append(float(parts[0]))
            if len(labels) != len(indptr) - 1:
                raise ValueError(
                    "label_libsvm has %d rows, data has %d"
                    % (len(labels), len(indptr) - 1))
        self._indptr = onp.asarray(indptr, dtype=onp.int64)
        self._indices = onp.asarray(indices, dtype=onp.int64)
        self._values = onp.asarray(values, dtype=onp.float32)
        self._labels = onp.asarray(labels, dtype=onp.float32)
        self._ncol = ncol
        self.round_batch = round_batch
        self._cursor = 0
        self.num_data = len(self._labels)

    @staticmethod
    def _parse_native(path, inline_labels):
        """Compiled multithreaded parse (native/textio.cc — the analog of
        iter_libsvm.cc's C++ tokenizer). None → Python fallback."""
        from .._native import textlib

        if textlib is None:
            return None
        h = textlib.svm_parse(str(path).encode(), 1 if inline_labels else 0)
        if not h:
            from ..base import MXNetError

            raise MXNetError(
                f"libsvm parse failed: "
                f"{textlib.textio_last_error().decode()}")
        try:
            rows, nnz = textlib.svm_rows(h), textlib.svm_nnz(h)

            def arr(ptr, n, dtype):
                if n == 0:
                    return onp.zeros(0, dtype)
                return onp.ctypeslib.as_array(ptr, shape=(n,)).copy()

            values = arr(textlib.svm_data(h), nnz, "f")
            indices = arr(textlib.svm_indices(h), nnz, onp.int64)
            indptr = arr(textlib.svm_indptr(h), rows + 1, onp.int64)
            labels = (arr(textlib.svm_labels(h), rows, "f")
                      if inline_labels else onp.zeros(0, "f"))
            return values, indices, indptr, list(labels)
        finally:
            textlib.svm_free(h)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._ncol))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        from .. import nd
        from ..ndarray.sparse import csr_matrix

        if self._cursor >= self.num_data:
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self.num_data)
        self._cursor = lo + self.batch_size
        pad = self.batch_size - (hi - lo)
        rows = list(range(lo, hi)) + \
            [i % self.num_data for i in range(pad)]
        ip = [0]
        ind, val = [], []
        for r in rows:
            s, e = self._indptr[r], self._indptr[r + 1]
            ind.extend(self._indices[s:e])
            val.extend(self._values[s:e])
            ip.append(len(ind))
        data = csr_matrix((onp.array(val, dtype=onp.float32),
                           onp.array(ind, dtype=onp.int64),
                           onp.array(ip, dtype=onp.int64)),
                          shape=(self.batch_size, self._ncol))
        label = self._labels[[r for r in rows]]
        return DataBatch(data=[data], label=[nd.array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
