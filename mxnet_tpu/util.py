"""General utilities (reference: python/mxnet/util.py).

The numpy-semantics switches (np_shape / np_array / use_np*) share one
state with ``mxnet_tpu.numpy_extension`` — this module adds the
context-manager/decorator forms and the small filesystem/introspection
helpers the reference exposes at ``mx.util``.
"""
from __future__ import annotations

import functools
import os
import sys

from . import numpy_extension as _npx

__all__ = ["makedirs", "set_np_shape", "is_np_shape", "np_shape",
           "use_np_shape", "np_array", "is_np_array", "use_np_array",
           "use_np", "set_np", "reset_np", "set_module", "wraps_safely",
           "get_gpu_count", "get_gpu_memory"]


def makedirs(d):
    """mkdir -p (reference: util.py makedirs)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count():
    """Number of accelerator devices (TPU chips here; reference counts
    CUDA GPUs)."""
    import jax

    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except RuntimeError:
        return 0


def get_gpu_memory(gpu_dev_id=0):
    """(free, total) accelerator memory in bytes, when the backend
    exposes it (reference: cudaMemGetInfo). Single source of truth for
    the math: storage.device_memory_info."""
    import jax

    from .storage import device_memory_info

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if gpu_dev_id >= len(devs):
        raise ValueError(f"no accelerator device {gpu_dev_id}")
    free, total, _ = device_memory_info(devs[gpu_dev_id])
    return free, total


# ---- numpy-semantics switches (shared state with numpy_extension) --------

def set_np_shape(active):
    """Enable/disable NumPy shape semantics (zero-dim/zero-size arrays).
    Returns the previous state (reference: util.py set_np_shape)."""
    prev = _npx._NP_SHAPE
    _npx._NP_SHAPE = bool(active)
    return prev


def is_np_shape():
    return _npx.is_np_shape()


def is_np_array():
    return _npx.is_np_array()


class _Scope:
    """Context manager + decorator toggling one switch (reference
    _NumpyShapeScope/_NumpyArrayScope)."""

    def __init__(self, attr, active):
        self._attr = attr
        self._active = bool(active)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_npx, self._attr)
        setattr(_npx, self._attr, self._active)
        return self

    def __exit__(self, *exc):
        setattr(_npx, self._attr, self._prev)
        return False

    def __call__(self, func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with _Scope(self._attr, self._active):
                return func(*args, **kwargs)

        return wrapper


def np_shape(active=True):
    """``with np_shape():`` or ``@np_shape()`` (reference util.np_shape)."""
    return _Scope("_NP_SHAPE", active)


def np_array(active=True):
    return _Scope("_NP_ARRAY", active)


use_np_shape = _npx.use_np_shape
use_np_array = _npx.use_np_array
use_np = _npx.use_np
set_np = _npx.set_np
reset_np = _npx.reset_np


def wraps_safely(wrapped, assigned=functools.WRAPPER_ASSIGNMENTS):
    """functools.wraps tolerating missing attributes (reference:
    util.py wraps_safely)."""
    present = [a for a in assigned if hasattr(wrapped, a)]
    return functools.wraps(wrapped, assigned=present)


def set_module(module):
    """Decorator overriding __module__ for doc tooling (reference:
    util.py set_module)."""

    def deco(obj):
        if module is not None:
            obj.__module__ = module
        return obj

    return deco
