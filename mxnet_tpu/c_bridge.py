"""Python side of the flat C ABI (``native/c_api.cc``).

Reference: ``src/c_api/c_api.cc`` (NDArray + imperative invoke entry
points) and ``src/c_api/c_predict_api.cc`` (deploy-only predictor). The
reference's C API fronts its C++ runtime so non-C++ frontends and C/C++
applications can drive it; in this rebuild the runtime is Python/JAX, so
the C library attaches to (or embeds) CPython and calls the marshalling
helpers in this module. Everything here takes/returns only plain Python
objects (tuples, ints, bytes, NDArrays) so the C side stays a thin
argument-shuffling layer.
"""
from __future__ import annotations

import ast

import numpy as onp

from . import ndarray as nd
from .base import MXNetError
from .utils import compile_cache as _cc
from .ndarray import NDArray
from .ndarray.ndarray import _TYPE_FLAG_TO_DTYPE, _DTYPE_TO_TYPE_FLAG

__all__ = ["nd_create", "nd_shape", "nd_dtype", "nd_from_bytes",
           "nd_to_bytes", "nd_reshape", "nd_slice", "nd_save", "nd_load",
           "invoke", "wait_all", "CPredictor",
           "sym_var", "sym_create_atomic", "sym_compose", "sym_from_json",
           "sym_to_json", "sym_list", "sym_get_attr", "sym_set_attr",
           "exec_simple_bind", "exec_array",
           "exec_forward", "exec_backward", "exec_outputs",
           "kv_create", "kv_set_optimizer", "kv_init", "kv_push",
           "kv_pull", "kv_meta",
           "cached_op_create", "cached_op_invoke",
           "autograd_set_recording", "autograd_set_training",
           "autograd_mark_variables", "autograd_backward", "nd_get_grad",
           "profiler_config", "profiler_set_state", "profiler_dump",
           "profiler_stats_print", "random_seed"]


def nd_create(shape, dtype_flag):
    """MXNDArrayCreate: zero-initialized array (reference c_api.cc
    MXNDArrayCreateEx)."""
    return nd.zeros(tuple(int(s) for s in shape),
                    dtype=_TYPE_FLAG_TO_DTYPE[int(dtype_flag)])


def nd_shape(a):
    return tuple(int(s) for s in a.shape)


def nd_dtype(a):
    return int(_DTYPE_TO_TYPE_FLAG[str(a.dtype)])


def nd_from_bytes(a, buf):
    """MXNDArraySyncCopyFromCPU: overwrite `a` in place from raw bytes."""
    arr = onp.frombuffer(buf, dtype=str(a.dtype)).reshape(a.shape)
    a[:] = nd.array(arr, dtype=str(a.dtype))
    return None


def nd_to_bytes(a):
    """MXNDArraySyncCopyToCPU: raw little-endian bytes of the value."""
    return onp.ascontiguousarray(a.asnumpy()).tobytes()


def _parse_param(v):
    """C callers pass stringified params exactly like the reference C API
    ("(3, 3)", "True", "0.5", "relu") — parse literals, keep strings."""
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def invoke(op_name, inputs, keys, vals):
    """MXImperativeInvoke: run a registered operator by name.

    Reference: c_api_ndarray.cc MXImperativeInvokeEx. Returns a list of
    output NDArrays (ops with a single output return a 1-list).
    """
    # confine lookups to the operator registry (not arbitrary nd-module
    # attributes): the C surface must only reach registered operators
    from .ndarray import registry as _registry

    name = op_name
    if _registry.get_op(name) is None:
        from .ndarray import _CAMEL_ALIASES

        name = _CAMEL_ALIASES.get(op_name)
        if name is None or _registry.get_op(name) is None:
            raise MXNetError(f"unknown operator '{op_name}'")
    fn = getattr(nd, name)
    params = {k: _parse_param(v) for k, v in zip(keys, vals)}
    out = fn(*inputs, **params)
    if isinstance(out, (list, tuple)):
        return [o if isinstance(o, NDArray) else nd.array(o) for o in out]
    return [out if isinstance(out, NDArray) else nd.array(out)]


def wait_all():
    nd.waitall()
    return None


class CPredictor:
    """Deploy-only forward pass over an exported checkpoint.

    Reference: src/c_api/c_predict_api.cc MXPredCreate — loads a
    ``*-symbol.json`` graph plus ``*.params`` bytes (arg:/aux: prefixed),
    binds with fixed input shapes, and serves Forward/GetOutput. The
    whole graph compiles to one XLA executable on first forward.
    """

    def __init__(self, symbol_json, param_bytes, dev_type=1, dev_id=0,
                 input_shapes=None):
        from . import symbol as sym_mod

        self._sym = sym_mod.load_json(symbol_json)
        params = nd.load_frombuffer(param_bytes) if param_bytes else {}
        arg_params, aux_params = {}, {}
        if isinstance(params, dict):
            for k, v in params.items():
                if k.startswith("arg:"):
                    arg_params[k[4:]] = v
                elif k.startswith("aux:"):
                    aux_params[k[4:]] = v
                else:
                    arg_params[k] = v
        shapes = {k: tuple(int(d) for d in v)
                  for k, v in (input_shapes or {}).items()}
        self._input_names = sorted(shapes)
        self._exec = self._sym.simple_bind(grad_req="null", **shapes)
        # aux states (BatchNorm moving stats) load alongside args —
        # leaving them at bind-time defaults silently corrupts inference;
        # copy_params_from also rejects shape-mismatched checkpoints at
        # load time
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        # output shapes are known at bind time (simple_bind retains its
        # inference result), so C callers can size buffers before the
        # first Forward — reference c_predict_api.cc keeps out_shapes on
        # the handle from creation
        self._out_shapes = self._exec.output_shapes
        self._outputs = None

    def set_input(self, key, buf, shape=None):
        d = self._exec.arg_dict
        if key not in d:
            raise MXNetError(f"unknown input '{key}'; inputs are "
                             f"{self._input_names}")
        tgt = d[key]
        shape = tuple(shape) if shape else tgt.shape
        arr = onp.frombuffer(buf, dtype=str(tgt.dtype)).reshape(shape)
        tgt[:] = nd.array(arr, dtype=str(tgt.dtype))
        return None

    def forward(self):
        self._outputs = self._exec.forward(is_train=False)
        return None

    def num_outputs(self):
        if self._outputs is not None:
            return len(self._outputs)
        return len(self._out_shapes)

    def output_shape(self, index):
        """Known from bind-time shape inference — valid before forward()
        (reference MXPredGetOutputShape works right after MXPredCreate)."""
        if self._outputs is not None:
            return tuple(int(s) for s in self._outputs[index].shape)
        return tuple(int(s) for s in self._out_shapes[index])

    def output_bytes(self, index):
        """Output `index` as float32 little-endian bytes (the C predict
        API is float-only, matching the reference's MXPredGetOutput)."""
        self._ensure_forward()
        return onp.ascontiguousarray(
            self._outputs[index].asnumpy().astype("float32")).tobytes()

    def _ensure_forward(self):
        if self._outputs is None:
            raise MXNetError("call forward() before reading outputs")

    def reshape(self, input_shapes):
        """MXPredReshape: rebind with new input shapes, keeping weights
        AND aux states (a rebind that resets BN running stats would serve
        garbage after the first reshape)."""
        old_args = dict(zip(self._exec.arg_names, self._exec.arg_arrays))
        old_aux = dict(self._exec.aux_dict)
        shapes = {k: tuple(int(d) for d in v)
                  for k, v in input_shapes.items()}
        self._exec = self._sym.simple_bind(grad_req="null", **shapes)
        for name, arr in zip(self._exec.arg_names, self._exec.arg_arrays):
            if name in old_args and name not in shapes and \
                    tuple(old_args[name].shape) == tuple(arr.shape):
                arr[:] = old_args[name]
        for name, arr in self._exec.aux_dict.items():
            if name in old_aux and tuple(old_aux[name].shape) == \
                    tuple(arr.shape):
                arr[:] = old_aux[name]
        self._out_shapes = self._exec.output_shapes
        self._outputs = None
        return None


# ---------------------------------------------------------------- symbol ---
# Symbol handles on the C side are one-element lists ("cells"): the
# reference's MXSymbolCompose mutates its handle in place
# (src/c_api/c_api_symbolic.cc Compose), and a cell lets the bridge swap
# the underlying Symbol while the C caller keeps one stable pointer.


class _AtomicOp:
    """An operator with bound params awaiting composition (reference:
    MXSymbolCreateAtomicSymbol before MXSymbolCompose). Attrs set before
    composition (the reference's normal ordering) are held and stamped
    onto the composed node."""

    __slots__ = ("op", "params", "attrs")

    def __init__(self, op, params):
        self.op = op
        self.params = params
        self.attrs = {}


def sym_var(name):
    from . import symbol as sym_mod

    # bare Symbol, NOT Variable(): the reference's MXSymbolCreateVariable
    # never consults the python-frontend AttrScope, so a C caller on a
    # thread that happens to be inside one must not get stamped attrs
    return [sym_mod.Symbol(op=None, name=name)]


def sym_create_atomic(op_name, keys, vals):
    from . import symbol as sym_mod

    if not hasattr(sym_mod, op_name):
        raise MXNetError(f"unknown operator '{op_name}'")
    params = {k: _parse_param(v) for k, v in zip(keys, vals)}
    return [_AtomicOp(op_name, params)]


def sym_compose(cell, name, keys, arg_cells):
    """MXSymbolCompose: attach inputs, materializing the graph node."""
    from . import symbol as sym_mod

    node = cell[0]
    if not isinstance(node, _AtomicOp):
        raise MXNetError("handle was already composed")
    fn = getattr(sym_mod, node.op)
    inputs = [c[0] for c in arg_cells]
    if any(isinstance(i, _AtomicOp) for i in inputs):
        raise MXNetError("composition argument is not composed yet")
    kwargs = dict(node.params)
    if name:
        kwargs["name"] = name
    if keys:  # named inputs (reference kwarg composition)
        for k, s in zip(keys, inputs):
            kwargs[k] = s
        cell[0] = fn(**kwargs)
    else:
        cell[0] = fn(*inputs, **kwargs)
    if node.attrs:  # attrs set before composition carry over
        cell[0]._set_attr(**node.attrs)
    return None


def sym_from_json(js):
    from . import symbol as sym_mod

    return [sym_mod.load_json(js)]


def sym_to_json(cell):
    return _composed(cell).tojson()


def _composed(cell):
    s = cell[0]
    if isinstance(s, _AtomicOp):
        raise MXNetError("symbol is not composed yet (call MXSymbolCompose)")
    return s


def sym_list(cell, kind):
    s = _composed(cell)
    if kind == "arguments":
        return list(s.list_arguments())
    if kind == "aux":
        return list(s.list_auxiliary_states())
    if kind == "outputs":
        return list(s.list_outputs())
    raise MXNetError(f"unknown list kind '{kind}'")


# -------------------------------------------------------------- executor ---

def exec_simple_bind(cell, grad_req, input_shapes):
    shapes = {k: tuple(int(d) for d in v) for k, v in input_shapes.items()}
    return _composed(cell).simple_bind(grad_req=grad_req, **shapes)


def exec_array(ex, kind, name):
    """Borrow a bound array by name: kind arg|grad|aux. The returned
    handle aliases the executor's storage, so MXNDArraySyncCopyFromCPU
    into it feeds the next forward (reference: executor arg_dict)."""
    table = {"arg": ex.arg_dict, "grad": ex.grad_dict,
             "aux": ex.aux_dict}.get(kind)
    if table is None:
        raise MXNetError(f"unknown array kind '{kind}'")
    if name not in table:
        raise MXNetError(f"no {kind} array named '{name}'")
    return table[name]


def exec_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))
    return None


def exec_outputs(ex):
    return list(ex.outputs)


def exec_backward(ex):
    ex.backward()
    return None


# --------------------------------------------------------------- kvstore ---

def kv_create(kind):
    from . import kvstore as kvs

    return kvs.create(kind)


def kv_set_optimizer(kv, opt_name, keys, vals):
    from . import optimizer as opt_mod

    params = {k: _parse_param(v) for k, v in zip(keys, vals)}
    kv.set_optimizer(opt_mod.create(opt_name, **params))
    return None


def kv_init(kv, keys, vals):
    kv.init(list(keys), list(vals))
    return None


def kv_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=priority)
    return None


def kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=priority)
    return None


# ----------------------------------------------------- misc ABI surface ---

def nd_reshape(a, shape):
    return a.reshape(tuple(int(s) for s in shape))


def nd_slice(a, begin, end):
    return a[int(begin):int(end)]


def sym_get_attr(cell, key):
    """Returns (found, value): an attr explicitly set to "" is found=1
    with an empty value, distinct from unset (reference MXSymbolGetAttr
    semantics). Works on uncomposed atomic handles too."""
    s = cell[0]
    v = s.attrs.get(key) if isinstance(s, _AtomicOp) else s.attr(key)
    return (0, "") if v is None else (1, str(v))


def sym_set_attr(cell, key, value):
    s = cell[0]
    if isinstance(s, _AtomicOp):
        s.attrs[key] = str(value)
    else:
        s._set_attr(**{key: value})
    return None


def kv_meta(kv, what):
    if what == "type":
        return str(kv.type)
    if what == "rank":
        return int(kv.rank)
    if what == "num_workers":
        return int(kv.num_workers)
    raise MXNetError(f"unknown kvstore meta '{what}'")


def nd_save(fname, keys, vals):
    """MXNDArraySave: write the reference-format .params file. Pairs,
    not a dict — the reference writes duplicate names sequentially and
    a dict would silently drop all but the last."""
    if keys and len(keys) != len(vals):
        raise MXNetError(
            f"MXNDArraySave: {len(keys)} keys for {len(vals)} arrays")
    if keys:
        nd.save(fname, list(zip(keys, vals)))
    else:
        nd.save(fname, list(vals))
    return None


def nd_load(fname):
    """MXNDArrayLoad: (names, arrays) with duplicates PRESERVED — the
    reference returns parallel arrays, unlike python mx.nd.load's
    dict view. Magic-checked: non-reference formats (the npz container
    earlier package versions wrote) go through the ordinary loader
    instead of being misparsed as a header."""
    import struct

    from .ndarray import ndarray as _impl

    with open(fname, "rb") as f:
        buf = f.read()
    if len(buf) >= 8 and             struct.unpack_from("<Q", buf, 0)[0] == _impl._LIST_MAGIC:
        names, arrays = _impl._load_ref_pairs(buf)
        return list(names), list(arrays)
    loaded = nd.load_frombuffer(buf)  # npz fallback (magic-checked)
    if isinstance(loaded, dict):
        return list(loaded.keys()), list(loaded.values())
    return [], list(loaded)


# ---- data iterators (reference: c_api.cc MXDataIterCreateIter family,
# src/io/iter_*.cc registrations) ---------------------------------------

_DATA_ITERS = ("MNISTIter", "ImageRecordIter", "CSVIter", "LibSVMIter")


def io_list():
    return list(_DATA_ITERS)


def _parse_io_param(v):
    """Iterator params: same literal parsing as _parse_param, plus the
    dmlc-style lowercase booleans the reference's iter params accept."""
    if v in ("true", "false"):
        return v == "true"
    return _parse_param(v)


def io_create(name, keys, vals):
    from . import io as mxio

    if name not in _DATA_ITERS:
        raise MXNetError(
            f"unknown data iter '{name}'; available: {_DATA_ITERS}")
    kwargs = {k: _parse_io_param(v) for k, v in zip(keys, vals)}
    it = getattr(mxio, name)(**kwargs)
    it._c_batch = None
    return it


def io_next(it):
    try:
        it._c_batch = next(it)
        return 1
    except StopIteration:
        it._c_batch = None
        return 0


def io_before_first(it):
    it.reset()
    it._c_batch = None


def _io_cur(it):
    if it._c_batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    return it._c_batch


def io_data(it):
    return _io_cur(it).data[0]


def io_label(it):
    lab = _io_cur(it).label
    if not lab:
        raise MXNetError("iterator has no label array")
    return lab[0]


def io_pad(it):
    return int(getattr(_io_cur(it), "pad", 0) or 0)


# ---- CachedOp (reference: c_api_ndarray.cc MXCreateCachedOp /
# MXInvokeCachedOp — the hybridize engine exposed over the C ABI) --------

class CCachedOp:
    """Symbol bound as a reusable callable. Inputs are positional in
    list_arguments() + list_auxiliary_states() order. Outside autograd
    recording, forward runs through one jit-compiled callable per input
    signature (the 'cached' part); while recording it runs eagerly so
    the tape sees every op."""

    def __init__(self, cell):
        # symbol handles cross the ABI as 1-element lists (see sym_var)
        self._sym = cell[0] if isinstance(cell, list) else cell
        self._names = list(self._sym.list_arguments()) + \
            list(self._sym.list_auxiliary_states())
        self._jitted = {}

    def __call__(self, inputs):
        from . import autograd

        if len(inputs) != len(self._names):
            raise MXNetError(
                f"CachedOp expects {len(self._names)} inputs "
                f"({self._names}), got {len(inputs)}")
        feed = dict(zip(self._names, inputs))
        if autograd.is_recording():
            out = self._sym.eval_with(feed)
        else:
            import jax

            from . import random as _mxrandom
            from .ndarray import registry as _registry

            # cache key mirrors gluon CachedOp (block.py): mode-dependent
            # ops (dropout/BN) and AMP casts bake into the trace, and a
            # PRNG key rides as an ARGUMENT so stochastic ops draw fresh
            # randomness per call instead of replaying the traced mask
            sig = (tuple((a.shape, str(a.dtype)) for a in inputs),
                   autograd.is_training(), _registry.amp_version())
            fn = self._jitted.get(sig)
            if fn is None:
                train = autograd.is_training()

                def run(datas, key):
                    with _mxrandom.key_provider(key), \
                            autograd._scope(training=train):
                        f = {n: NDArray(d)
                             for n, d in zip(self._names, datas)}
                        o = self._sym.eval_with(f)
                    if isinstance(o, (list, tuple)):
                        return [x.data for x in o]
                    return o.data

                fn = self._jitted[sig] = _cc.counting_jit(run, label="cached_op")
            res = fn([a.data for a in inputs], _mxrandom.next_key())
            out = [NDArray(r) for r in res] if isinstance(res, list) \
                else NDArray(res)
        return out if isinstance(out, list) else \
            list(out) if isinstance(out, tuple) else [out]


def cached_op_create(cell):
    return CCachedOp(cell)


def cached_op_invoke(cop, inputs):
    return cop(list(inputs))


# ---- autograd over the C ABI (reference: c_api_ndarray.cc
# MXAutogradSetIsRecording/MXAutogradMarkVariables/MXAutogradBackwardEx,
# src/c_api/c_api_ndarray.cc:81-143) -------------------------------------

def autograd_set_recording(flag):
    from . import autograd

    prev = autograd.is_recording()
    if flag and not prev:
        # fresh top-level record over the ABI: drop any stale tape a
        # backward-less forward left behind (same bounded-memory rule
        # as autograd._scope on entering record())
        autograd._STATE.tape = []
    autograd.set_recording(bool(flag))
    return int(prev)


def autograd_set_training(flag):
    from . import autograd

    prev = autograd.is_training()
    autograd.set_training(bool(flag))
    return int(prev)


_GRAD_REQ_NAMES = {0: "null", 1: "write", 2: "add"}


def autograd_mark_variables(variables, grad_reqs, gradients):
    from . import autograd

    reqs = [_GRAD_REQ_NAMES.get(int(r), "write") for r in grad_reqs]
    grads = [None if g is None else g for g in gradients]
    autograd.mark_variables(list(variables), grads, reqs)
    return None


def autograd_backward(outputs, head_grads, retain_graph, train_mode):
    from . import autograd

    heads = list(outputs)
    hg = None if head_grads is None else list(head_grads)
    autograd.backward(heads, hg, retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))
    return None


def nd_get_grad(a):
    if a.grad is None:
        raise MXNetError("array has no gradient buffer "
                         "(call MXAutogradMarkVariables first)")
    return a.grad


# ---- profiler over the C ABI (reference: src/c_api/c_api_profile.cc
# MXSetProcessProfilerConfig/State, MXDumpProcessProfile,
# MXAggregateProfileStatsPrint) ------------------------------------------

def profiler_config(keys, vals):
    from . import profiler

    kwargs = {k: _parse_param(v) for k, v in zip(keys, vals)}
    profiler.set_config(**kwargs)
    return None


_PROF_PAUSED = [False]


def profiler_set_state(state):
    from . import profiler

    state = int(state)
    if state == 2:
        profiler.pause()
        _PROF_PAUSED[0] = True
    elif state == 1:
        if _PROF_PAUSED[0]:
            profiler.resume()
            _PROF_PAUSED[0] = False
        else:
            profiler.set_state("run")
    else:
        _PROF_PAUSED[0] = False
        profiler.set_state("stop")
    return None


def profiler_dump(finished):
    from . import profiler

    profiler.dump(finished=bool(finished))
    return None


def profiler_stats_print(reset):
    from . import profiler

    return profiler.dumps(reset=bool(reset))


def random_seed(s):
    from . import random as _r

    _r.seed(int(s))
    return None


# ---- operator introspection (reference: c_api.cc MXListAllOpNames,
# MXSymbolListAtomicSymbolCreators / MXSymbolGetAtomicSymbolInfo — the
# surface every frontend uses to AUTOGENERATE its op bindings) -----------

def list_all_op_names():
    from .ndarray import registry as _registry

    return list(_registry.list_ops())


def op_info(op_name):
    """(name, doc, arg_names, arg_defaults_repr) for one registered op."""
    from .ndarray import registry as _registry

    opdef = _registry.get_op(op_name)
    if opdef is None:
        raise MXNetError(f"unknown operator '{op_name}'")
    try:
        sig = opdef.signature()
        args, defaults = [], []
        for p in sig.parameters.values():
            if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
                args.append("*" + p.name if p.kind == p.VAR_POSITIONAL
                            else "**" + p.name)
                defaults.append("")
            else:
                args.append(p.name)
                defaults.append("" if p.default is p.empty
                                else repr(p.default))
    except (TypeError, ValueError):
        args, defaults = [], []
    return (opdef.name, opdef.doc or "", args, defaults)


def sym_infer_shape(cell, keys, shapes):
    """MXSymbolInferShape: partial shape inference from named input
    shapes; returns (arg_names, arg_shapes, out_shapes, aux_names,
    aux_shapes) with None for undetermined entries."""
    from .symbol.infer import infer_shapes

    symb = _composed(cell)
    known = {k: tuple(int(d) for d in s) for k, s in zip(keys, shapes)}
    # infer_shapes gives the full var map, so aux shapes come back too
    # (infer_shape_partial drops them — reference MXSymbolInferShape
    # reports aux shapes, frontends allocate moving stats from them)
    var_shapes, out_shapes = infer_shapes(symb, known,
                                          allow_unknown=True)
    args = symb.list_arguments()
    auxs = symb.list_auxiliary_states()
    return (args, [var_shapes.get(a) for a in args], list(out_shapes),
            auxs, [var_shapes.get(a) for a in auxs])


def sym_infer_type(cell, keys, dtype_flags):
    """MXSymbolInferType: dtype inference from named input type flags."""
    symb = _composed(cell)
    known = {k: _TYPE_FLAG_TO_DTYPE[int(f)]
             for k, f in zip(keys, dtype_flags)}
    arg_types, out_types, aux_types = symb.infer_type(**known)

    def flags(ts):
        return [-1 if t is None else int(_DTYPE_TO_TYPE_FLAG[str(
            onp.dtype(t))]) for t in ts]

    return (symb.list_arguments(), flags(arg_types), flags(out_types),
            symb.list_auxiliary_states(), flags(aux_types))


def kv_barrier(kv):
    kv.barrier()
    return None


def kv_pushpull(kv, keys, vals, outs, priority):
    kv.pushpull(list(keys), list(vals), out=list(outs),
                priority=int(priority))
    return None


def nd_at(a, idx):
    """MXNDArrayAt: view of row `idx` (reference c_api.cc MXNDArrayAt)."""
    return a[int(idx)]


def nd_context(a):
    """(dev_type, dev_id) — reference dev_type codes via
    Context.devstr2type (one source of truth, context.py)."""
    ctx = a.context
    return (int(getattr(ctx, "device_typeid", 1)),
            int(getattr(ctx, "device_id", 0)))
