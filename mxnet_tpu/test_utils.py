"""Test helpers (reference: python/mxnet/test_utils.py, 2386 LoC — the
de-facto harness for the reference's whole unittest suite; SURVEY §4).

check_consistency's CPU↔GPU oracle becomes a CPU↔TPU / eager↔jit oracle
here: the same op is run on each available backend (or both eagerly and
under jit) and compared.
"""
from __future__ import annotations

import numbers

import numpy as onp

from . import context as _ctx_mod
from .context import Context, cpu, current_context


def default_context():
    return current_context()


def set_default_context(ctx):
    Context._default_ctx.value = ctx


def same(a, b):
    return onp.array_equal(onp.asarray(a), onp.asarray(b))


def _as_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return onp.asarray(x)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    """Reference: test_utils.py assert_almost_equal (relative+absolute)."""
    a = _as_numpy(a)
    b = _as_numpy(b)
    if a.shape != b.shape:
        raise AssertionError(
            f"shape mismatch: {names[0]}{a.shape} vs {names[1]}{b.shape}")
    if onp.allclose(a.astype(onp.float64), b.astype(onp.float64),
                    rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    diff = onp.abs(a.astype(onp.float64) - b.astype(onp.float64))
    denom = onp.maximum(onp.abs(b.astype(onp.float64)), atol)
    rel = diff / onp.maximum(denom, 1e-300)
    idx = onp.unravel_index(onp.argmax(rel), rel.shape)
    raise AssertionError(
        f"{names[0]} and {names[1]} differ: max rel err {rel.max():.3g} "
        f"at {idx} ({a[idx]} vs {b[idx]}), rtol={rtol} atol={atol}")


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(onp.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, distribution="uniform"):
    from . import nd
    from .ndarray import sparse

    dtype = dtype or "float32"
    if distribution == "normal":
        arr = onp.random.normal(size=shape).astype(dtype)
    else:
        arr = onp.random.uniform(size=shape).astype(dtype)
    if stype in ("row_sparse", "csr"):
        density = 0.5 if density is None else density
        mask = onp.random.uniform(size=shape) < density
        if stype == "row_sparse":
            mask = onp.broadcast_to(
                mask.reshape(shape[0], -1).any(axis=1)
                .reshape((-1,) + (1,) * (len(shape) - 1)), shape)
        arr = onp.where(mask, arr, onp.zeros_like(arr))
        return sparse.cast_storage(nd.array(arr), stype)
    return nd.array(arr, dtype=dtype)


def numeric_grad(executor_fn, x, eps=1e-4):
    """Central finite differences of a scalar function at x (numpy)."""
    x = onp.asarray(x, dtype=onp.float64)
    g = onp.zeros_like(x)
    it = onp.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + eps
        fp = float(executor_fn(x))
        x[idx] = orig - eps
        fm = float(executor_fn(x))
        x[idx] = orig
        g[idx] = (fp - fm) / (2 * eps)
        it.iternext()
    return g


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-4, eps=1e-3):
    """Compare autograd gradients of `fn` against finite differences.

    fn: NDArray... -> scalar NDArray (summed if not scalar).
    inputs: list of numpy arrays. Reference: test_utils.py
    check_numeric_gradient (finite-difference oracle)."""
    from . import nd, autograd

    nds = [nd.array(onp.asarray(a, dtype="float32")) for a in inputs]
    for a in nds:
        a.attach_grad()
    with autograd.record():
        out = fn(*nds)
        loss = nd.sum(out)
    loss.backward()
    analytic = [a.grad.asnumpy() for a in nds]

    for i, base in enumerate(inputs):
        def f(x, _i=i):
            args = [nd.array(onp.asarray(a, dtype="float32"))
                    if j != _i else nd.array(x.astype("float32"))
                    for j, a in enumerate(inputs)]
            return float(nd.sum(fn(*args)).asnumpy())

        num = numeric_grad(f, onp.asarray(base, dtype=onp.float64), eps)
        assert_almost_equal(analytic[i], num, rtol=rtol, atol=atol,
                            names=(f"analytic[{i}]", f"numeric[{i}]"))


_TOLS = {
    # dtype -> (rtol, atol): the reference's per-dtype tolerance ladder
    # (test_utils.py get_tols / default_rtols). bfloat16 has 8 mantissa
    # bits, float16 has 10 — bf16 gets the loosest rungs.
    "float64": (1e-12, 1e-14),
    "float32": (1e-5, 1e-7),
    "float16": (1e-2, 1e-4),
    "bfloat16": (4e-2, 1e-3),
    "int64": (0, 0), "int32": (0, 0), "int8": (0, 0), "uint8": (0, 0),
    "bool": (0, 0),
}


def default_tols(dtype):
    """(rtol, atol) for comparisons at `dtype` (reference: get_tols)."""
    return _TOLS.get(str(onp.dtype(dtype) if dtype != "bfloat16"
                         else "bfloat16"), (1e-5, 1e-7))


def effective_dtype(x):
    """dtype name of an NDArray/array, normalizing bfloat16."""
    d = getattr(x, "dtype", None)
    return "bfloat16" if "bfloat16" in str(d) else str(onp.dtype(d))


def with_seed(seed=None):
    """Per-test deterministic seeding with the seed printed on failure
    (reference: common.py with_seed — the harness every reference
    unittest runs under)."""
    import functools
    import os
    import sys

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            from . import random as mx_random

            from . import env as _env

            this = seed if seed is not None else \
                _env.get_int("MXNET_TEST_SEED",
                             onp.random.randint(0, 2**31))
            onp.random.seed(this)
            mx_random.seed(this)
            try:
                return fn(*args, **kwargs)
            except BaseException:
                print(f"*** with_seed: test failed with seed={this}; "
                      f"reproduce with MXNET_TEST_SEED={this} ***",
                      file=sys.stderr)
                raise

        return wrapper

    return deco


def _cast_for(dtype, arr):
    import jax.numpy as jnp

    if dtype == "bfloat16":
        return jnp.asarray(arr).astype(jnp.bfloat16)
    return onp.asarray(arr).astype(dtype)


def check_consistency(fn, inputs, rtol=None, atol=None, dtype="float32",
                      ref_fn=None, compare_with_fp32=True):
    """Run `fn` eagerly and under jax.jit at `dtype` and compare — the
    rebuild's analog of the reference's CPU-vs-GPU check_consistency
    oracle (tests/python/gpu/test_operator_gpu.py re-runs the whole CPU
    suite through it). With `ref_fn` (or for non-fp32 dtypes) the result
    is additionally checked against the float32 eager run within the
    dtype's tolerance rung."""
    import jax

    from . import nd

    if rtol is None or atol is None:
        dr, da = default_tols(dtype)
        rtol = dr if rtol is None else rtol
        atol = da if atol is None else atol
    nds = [nd.NDArray(jax.numpy.asarray(_cast_for(dtype, a)))
           if not isinstance(a, nd.NDArray) else a for a in inputs]
    eager = fn(*nds)
    eager_list = eager if isinstance(eager, (list, tuple)) else [eager]

    def pure(*datas):
        outs = fn(*[nd.NDArray(d) for d in datas])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return tuple(o.data for o in outs)

    jitted = jax.jit(pure)(*[a.data for a in nds])  # graft-lint: allow(jit-nocache)
    for e, j in zip(eager_list, jitted):
        assert_almost_equal(e, onp.asarray(j.astype(jax.numpy.float32)),
                            rtol=rtol, atol=atol, names=("eager", "jit"))
    if compare_with_fp32 and str(dtype) in ("float16", "bfloat16"):
        # half-precision result must track the fp32 oracle within the
        # ladder rung (values, not just eager/jit agreement)
        ref = (ref_fn or fn)(*[nd.array(onp.asarray(a, dtype="float32"))
                               if not isinstance(a, nd.NDArray) else a
                               for a in inputs])
        ref_list = ref if isinstance(ref, (list, tuple)) else [ref]
        for e, r in zip(eager_list, ref_list):
            assert_almost_equal(
                onp.asarray(e.data.astype(jax.numpy.float32)),
                r.asnumpy(), rtol=rtol, atol=atol,
                names=(str(dtype), "float32_ref"))
    return eager


def discard_stderr(fn):  # decorator used by reference tests
    return fn
