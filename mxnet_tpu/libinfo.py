"""Library discovery + version (reference: python/mxnet/libinfo.py).

The reference locates libmxnet.so for the ctypes bridge; here the native
runtime is ``mxnet_tpu/_native/libmxnet_c.so`` (built on demand) and the
compute backend is in-process JAX/XLA, so find_lib_path returns the flat
C ABI library instead.
"""
from __future__ import annotations

import os

__version__ = "0.1.0"


def find_lib_path(prefix="libmxnet"):
    """Paths of the native C-ABI library matching `prefix`, building it
    if a toolchain is available (reference libinfo.py:26)."""
    from ._native import build_c_api

    so = build_c_api()
    if so and os.path.basename(so).startswith(prefix):
        return [so]
    return []


def find_include_path():
    """Directory of the public C headers (reference libinfo.py:79)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inc = os.path.join(here, "include")
    return inc if os.path.isdir(inc) else ""


def features():
    """Runtime feature flags (see mxnet_tpu.runtime for the full API)."""
    from . import runtime

    return runtime.Features()
