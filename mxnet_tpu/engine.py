"""Dependency engine: async host-task scheduling with var dependencies.

Reference: include/mxnet/engine.h + src/engine/threaded_engine*.cc — the
architectural heart of the reference runtime (every mutation flows through
it). On TPU, XLA's runtime already orders *device* computations, so this
engine owns the HOST side of that contract: IO pipelines, checkpoint
writes, custom-op bodies, metric sinks. The native core
(native/engine.cc, loaded via ctypes) implements var versioning, per-var
waiter FIFOs, a priority worker pool, and async exception propagation —
an op's exception poisons its mutable vars and is rethrown at the next
sync point (`wait_for_var`), matching the reference's deferred-raise
semantics (threaded_engine.h:466-498, tests test_exc_handling.py).
`MXNET_ENGINE_TYPE=NaiveEngine` selects the synchronous pure-Python
fallback (reference: src/engine/naive_engine.cc).
"""
from __future__ import annotations

import ctypes
import os
import threading

# module-level on purpose: push() is the framework's hottest host path,
# and the disarmed fault seam must cost one global read, not an import
# lookup per call (resilience.faults has no imports back into engine)
from .resilience import faults as _faults
from .utils import locks as _locks

__all__ = ["Engine", "NaiveEngine", "get", "var", "push", "wait_for_var",
           "wait_all", "LANE_COMPUTE", "LANE_IO"]

_CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


class _Var:
    __slots__ = ("id",)

    def __init__(self, vid):
        self.id = vid


#: named lanes over the per-lane worker pools (ThreadedEnginePerDevice
#: analog — threaded_engine_perdevice.cc runs a pool per device plus
#: dedicated copy workers; on TPU device compute is XLA-async, so the
#: split that matters is compute vs host copy/IO)
LANE_COMPUTE = 0
LANE_IO = 1


class Engine:
    """Threaded native engine (reference: ThreadedEnginePerDevice —
    `nlanes` independent worker pools over one dependency state; push
    with ``lane=LANE_IO`` to keep slow IO from starving compute ops)."""

    def __init__(self, nthreads=None, nlanes=None):
        from . import _native

        if _native.englib is None:
            raise RuntimeError("native engine library unavailable")
        self._lib = _native.englib
        from . import env as _env

        nthreads = nthreads or _env.get_int(
            "MXNET_CPU_WORKER_NTHREADS", os.cpu_count() or 4)
        nlanes = nlanes or _env.get_int("MXNET_ENGINE_NUM_LANES", 2)
        # guards: _active, _var_poison, _exceptions, _live_cbs, _h
        self._lock = _locks.RankedLock("engine.waiters")
        # close() coordination: _active counts threads inside a native
        # call on the handle (close must not destroy it under them);
        # _drained flips once close() has fully drained + destroyed, so
        # post-close callers can order themselves after every pre-close
        # op (a wait_for_var racing close() must NOT return before the
        # op writing its slot ran — that silently loses the write)
        self._cond = _locks.RankedCondition(lock=self._lock)
        self._active = 0
        self._drained = threading.Event()
        self._var_poison = {}  # var id -> exception, frozen at close()
        self._exceptions = {}  # op_id -> exception
        self._live_cbs = {}  # op_id -> (callback, ctx) keepalive
        self._h = self._lib.eng_create_lanes(int(nthreads), int(nlanes))
        self._nlanes = int(nlanes)

    def _reserve(self):
        """Pin the native handle for one call; None when closed. Every
        _reserve() pairs with _release() — close() destroys the handle
        only once no thread holds a reservation."""
        with self._lock:
            if self._h is None:
                return None
            self._active += 1
            return self._h

    def _release(self):
        with self._cond:
            self._active -= 1
            if self._active == 0:
                self._cond.notify_all()

    def new_variable(self):
        h = self._reserve()
        if h is None:  # closed: inline mode needs no real deps
            return _Var(-1)
        try:
            return _Var(self._lib.eng_new_var(h))
        finally:
            self._release()

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             lane=LANE_COMPUTE):
        """Schedule fn() after its deps; returns the op id. An exception
        in fn poisons `mutable_vars` and surfaces at wait_for_var."""
        # registered fault point: a failed host-task schedule (raises
        # synchronously in the pusher, like a dead worker pool)
        _faults.maybe_fail("engine_push")
        # deliberate unlocked read: close() only transitions _h to None
        # once, at atexit, and a push that loses the race blocks on the
        # drain event below — locking here would tax every op push
        if self._h is None:  # graft-lint: allow(L1102)
            # closed (atexit shutdown): run inline, but only after the
            # drain — an in-flight pre-close op may write the same
            # vars this fn depends on
            self._drained.wait()
            fn()
            return -1
        holder = {}
        inline = False

        def run(_ctx):
            try:
                fn()
                return 0
            except BaseException as e:  # noqa: BLE001 — deferred re-raise
                with self._lock:
                    self._exceptions[holder["op_id"]] = e
                return 1

        cb = _CB(run)
        cv = (ctypes.c_int64 * max(len(const_vars), 1))(
            *[v.id for v in const_vars])
        mv = (ctypes.c_int64 * max(len(mutable_vars), 1))(
            *[v.id for v in mutable_vars])
        # writer var ids only: WaitForVar barriers WRITERS of the var
        # (its sync op is itself a reader, and readers run concurrently),
        # so only ops holding the var mutable are provably finished when
        # a wait on it returns — GC'ing a reader's keepalive early would
        # free a trampoline the worker may still call
        writer_ids = frozenset(v.id for v in mutable_vars)
        with self._lock:
            if self._h is None:
                # close() swapped the handle between the unlocked check
                # above and here — fall through to inline execution
                # rather than hand NULL to the native library
                inline = True
            else:
                op_id = self._lib.eng_push_lane(
                    self._h, ctypes.cast(cb, ctypes.c_void_p), None, cv,
                    len(const_vars), mv, len(mutable_vars),
                    int(priority), int(lane))
                holder["op_id"] = op_id
                # keepalive carries the op's WRITER var set so
                # wait_for_var can GC it: after the wait returns, every
                # writer of that var has completed AND its trampoline
                # frame has returned (the native engine marks completion
                # after the callback returns), so steady-state pipelines
                # (IO iterators, nd.save) don't grow _live_cbs
                # unboundedly between wait_all barriers
                self._live_cbs[op_id] = (cb, writer_ids)
        if inline:
            self._drained.wait()
            fn()
            return -1
        return op_id

    def wait_for_var(self, v):
        """Block until all ops touching v finish; re-raise its poison.
        Racing close() is safe on both sides: a wait already inside the
        native call pins the handle (close drains first and the live
        pool completes the awaited op), and a wait arriving after the
        close blocks on the drain — so when it returns, every pre-close
        op touching v has truly run — then re-raises frozen poison."""
        h = self._reserve()
        if h is None:
            self._drained.wait()
            # post-drain read: the worker pool has quiesced, nothing
            # writes poison any more
            exc = self._var_poison.get(v.id)  # graft-lint: allow(L1102)
            if exc is not None:
                raise exc
            return
        try:
            # snapshot BEFORE the barrier: an op pushed concurrently
            # with the wait may still be running when it returns — only
            # ops registered before the wait are provably done (same
            # rule as wait_all)
            with self._lock:
                dead = [oid for oid, (_, var_ids) in self._live_cbs.items()
                        if v.id in var_ids]
            err_op = self._lib.eng_wait_for_var(h, v.id)
            # those ops have completed and their trampolines returned
            # (Complete runs after op->fn) — drop the keepalives
            with self._lock:
                for oid in dead:
                    self._live_cbs.pop(oid, None)
            if err_op >= 0:
                with self._lock:
                    exc = self._exceptions.get(err_op)
                if exc is not None:
                    raise exc
                raise RuntimeError(f"engine op {err_op} failed")
        finally:
            self._release()

    def wait_all(self):
        h = self._reserve()
        if h is None:
            self._drained.wait()
            return
        try:
            # snapshot BEFORE the barrier: a concurrent push() racing
            # with the barrier's return may register a new callback
            # whose op is still in flight — only ops pushed before the
            # barrier are provably done
            with self._lock:
                done_ids = list(self._live_cbs)
            self._lib.eng_wait_all(h)
            self._gc_callbacks(done_ids)
        finally:
            self._release()

    def var_version(self, v):
        h = self._reserve()
        if h is None:
            self._drained.wait()
            return 0
        try:
            return int(self._lib.eng_var_version(h, v.id))
        finally:
            self._release()

    def num_live_callbacks(self):
        with self._lock:
            return len(self._live_cbs)

    def _gc_callbacks(self, done_ids):
        # WaitForAll is a full barrier: every op pushed before it has
        # completed and its trampoline frame has returned, so no worker
        # can still be inside those ctypes callbacks — safe to drop their
        # keepalives. Poison exceptions stay (bounded by error count) so
        # a later wait_for_var on a still-poisoned var re-raises.
        with self._lock:
            for op_id in done_ids:
                self._live_cbs.pop(op_id, None)

    def close(self):
        """Drain in-flight ops and join the native worker pool.
        Idempotent; after close() pushes run inline (NaiveEngine-style)
        so late callers (atexit hooks, iterator teardown) stay correct.

        Ordering vs concurrent waiters (the DeviceFeed/DataLoader
        pipeline closes the engine mid-epoch in tests): (1) swap the
        handle out under the push lock (a racing push re-checks and
        goes inline); (2) wait for threads already inside a native call
        to return — their awaited ops complete on the still-live pool;
        (3) drain every pending op, freeze per-var poison for
        post-close wait_for_var, destroy, and only then flip _drained —
        the gate every post-close path (inline push, closed-path waits)
        blocks on, so no pre-close slot write can be skipped over. The
        drain runs OUTSIDE the lock — in-flight callbacks take the same
        lock to record exceptions, so holding it through eng_wait_all
        would deadlock. getattr guards: __del__ may see a
        half-constructed instance whose __init__ raised before _h/_lock
        were assigned."""
        lock = getattr(self, "_lock", None)
        if lock is None:
            return
        missing = object()
        with lock:
            h = getattr(self, "_h", missing)
            if h is missing:  # __init__ raised before the handle existed
                return
            self._h = None
        if h is None:
            # another close() owns (or finished) the drain — order after
            self._drained.wait()
            return
        try:
            with self._cond:
                while self._active > 0:
                    self._cond.wait()
            try:
                self._lib.eng_wait_all(h)
            except Exception:  # graft-lint: allow(L501)
                pass
            with lock:
                poison = {}
                for oid, (_, var_ids) in self._live_cbs.items():
                    exc = self._exceptions.get(oid)
                    if exc is not None:
                        for vid in var_ids:
                            poison[vid] = exc
                self._var_poison = poison
                self._live_cbs.clear()
            try:
                self._lib.eng_destroy(h)
            except Exception:  # graft-lint: allow(L501)
                pass
        finally:
            self._drained.set()

    def __del__(self):
        try:
            # finalizers interleave arbitrarily; this instance is
            # unreachable so its locks cannot be held elsewhere
            with _locks.exempt("gc finalizer on unreachable engine"):
                self.close()
        except Exception:  # graft-lint: allow(L501)
            pass


class NaiveEngine:
    """Synchronous debug engine (reference: naive_engine.cc) — executes on
    push, same exception-on-var semantics."""

    def __init__(self, nthreads=None):
        self._versions = {}
        self._errors = {}
        self._exceptions = {}
        self._next = 0

    def new_variable(self):
        v = _Var(self._next)
        self._next += 1
        self._versions[v.id] = 0
        return v

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             lane=0):
        _faults.maybe_fail("engine_push")
        op_id = self._next
        self._next += 1
        poisoned = [v for v in list(const_vars) + list(mutable_vars)
                    if v.id in self._errors]
        if poisoned:
            src = self._errors[poisoned[0].id]
            for v in mutable_vars:
                self._errors.setdefault(v.id, src)
                # native Complete() bumps versions for skipped ops too
                self._versions[v.id] += 1
            return op_id
        try:
            fn()
            for v in mutable_vars:
                self._versions[v.id] += 1
        except BaseException as e:  # noqa: BLE001
            self._exceptions[op_id] = e
            for v in mutable_vars:
                self._errors[v.id] = op_id
                # native Complete() bumps versions even on failure
                # (engine.cc) — keep the two engine types in lockstep
                self._versions[v.id] += 1
        return op_id

    def wait_for_var(self, v):
        if v.id in self._errors:
            raise self._exceptions[self._errors[v.id]]

    def wait_all(self):
        pass

    def var_version(self, v):
        return self._versions.get(v.id, 0)


_engine = None
# guards: _engine
_engine_lock = _locks.RankedLock("engine.singleton")


def get():
    """The process engine singleton (reference: Engine::Get(), selection
    via MXNET_ENGINE_TYPE — engine.cc:32-45)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            from . import env as _env

            etype = _env.get_str("MXNET_ENGINE_TYPE", "ThreadedEngine")
            if etype == "NaiveEngine":
                _engine = NaiveEngine()
            else:
                try:
                    _engine = Engine()
                except RuntimeError:
                    _engine = NaiveEngine()
        return _engine


def var():
    return get().new_variable()


def push(fn, const_vars=(), mutable_vars=(), priority=0, lane=0):
    return get().push(fn, const_vars, mutable_vars, priority, lane)


def wait_for_var(v):
    get().wait_for_var(v)


def wait_all():
    get().wait_all()


def _shutdown_at_exit():
    """Join the native worker pool BEFORE interpreter teardown.

    Without this, a process exiting with decode/IO ops still in flight
    tears down the Python runtime while a native worker is inside (or
    about to enter) a ctypes callback trampoline — an intermittent
    teardown segfault first seen in the train_imagenet_rec example
    subprocess (tests/test_examples_rec.py). atexit runs while Python is
    fully alive: drain every op, join the threads, and flip the engine
    to inline mode so any later atexit hook that pushes still runs."""
    global _engine
    with _engine_lock:
        eng = _engine
    if eng is not None and isinstance(eng, Engine):
        eng.close()


import atexit  # noqa: E402

atexit.register(_shutdown_at_exit)
