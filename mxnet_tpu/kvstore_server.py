"""KVStore server entry point (reference: python/mxnet/kvstore_server.py).

The reference's dist training topology has dedicated server/scheduler
processes (ps-lite) that own the global weights; workers push gradients
and pull weights. The TPU-native design has NO parameter servers: every
process is a worker, global state is sharded/replicated across the mesh,
and aggregation is an XLA all-reduce over ICI/DCN (see kvstore.py
dist_sync and parallel/spmd.py).

This module keeps launcher compatibility: scripts started with
DMLC_ROLE=server or =scheduler (reference launchers set these on the
extra processes) exit cleanly instead of importing mxnet and silently
training a duplicate worker — mirroring `_init_kvstore_server_module`'s
behavior of never returning control to the user script on non-worker
roles.
"""
from __future__ import annotations

import logging
import os
import sys

__all__ = ["KVStoreServer"]


class KVStoreServer:
    """API-parity shim for the reference's blocking server loop."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def _controller(self):
        def server_controller(cmd_id, cmd_body, _):
            logging.info("kvstore server command (%s, %s) ignored: the "
                         "TPU backend has no parameter-server role",
                         cmd_id, cmd_body)

        return server_controller

    def run(self):
        logging.info(
            "KVStoreServer.run(): no-op — aggregation runs as XLA "
            "collectives inside the worker step; there is no server "
            "process to host")


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role in ("server", "scheduler"):
        logging.warning(
            "DMLC_ROLE=%s: the TPU backend needs no %s processes "
            "(collectives replace ps-lite); exiting", role, role)
        sys.exit(0)


_init_kvstore_server_module()
