"""Detection image pipeline: box-aware augmenters + ImageDetIter.

Reference: python/mxnet/image/detection.py (1009 LoC). Labels use the
reference's packed format: [header_width, object_width, extra..., then
per-object (id, xmin, ymin, xmax, ymax, ...)] with coordinates
normalized to [0, 1].
"""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray
from ..io.io import DataBatch, DataDesc
from .image import (ImageIter, Augmenter, imresize, fixed_crop,
                    HorizontalFlipAug, CastAug, ColorNormalizeAug,
                    ColorJitterAug, HueJitterAug, RandomGrayAug,
                    ForceResizeAug, _to_numpy)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "DetRandomSelectAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Reference: detection.py:DetAugmenter — operates on (img, label)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter (reference: detection.py:112)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and x-coordinates (reference: detection.py:131)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = nd.array(_to_numpy(src)[:, ::-1].copy())
            label = label.copy()
            tmp = 1.0 - label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


def _box_iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) \
        - inter
    return inter / ua if ua > 0 else 0.0


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (reference: detection.py:164)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = _to_numpy(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range) * h * w
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            cw = int(round((area * ratio) ** 0.5))
            ch = int(round((area / ratio) ** 0.5))
            if cw > w or ch > h or cw <= 0 or ch <= 0:
                continue
            x0 = pyrandom.randint(0, w - cw)
            y0 = pyrandom.randint(0, h - ch)
            crop = (x0 / w, y0 / h, (x0 + cw) / w, (y0 + ch) / h)
            new_label = self._update_labels(label, crop)
            if new_label is None:
                continue
            out = fixed_crop(arr, x0, y0, cw, ch)
            return out, new_label
        return src, label

    def _update_labels(self, label, crop):
        cx0, cy0, cx1, cy1 = crop
        cw, chh = cx1 - cx0, cy1 - cy0
        out = []
        covered = False
        for row in label:
            box = row[1:5]
            inter = (max(box[0], cx0), max(box[1], cy0),
                     min(box[2], cx1), min(box[3], cy1))
            if inter[2] <= inter[0] or inter[3] <= inter[1]:
                continue
            barea = (box[2] - box[0]) * (box[3] - box[1])
            carea = (inter[2] - inter[0]) * (inter[3] - inter[1])
            coverage = carea / barea if barea > 0 else 0
            if coverage < self.min_eject_coverage:
                continue
            if coverage >= self.min_object_covered:
                covered = True
            new_row = row.copy()
            new_row[1] = (inter[0] - cx0) / cw
            new_row[2] = (inter[1] - cy0) / chh
            new_row[3] = (inter[2] - cx0) / cw
            new_row[4] = (inter[3] - cy0) / chh
            out.append(new_row)
        if not out or not covered:
            return None
        return onp.stack(out)


class DetRandomPadAug(DetAugmenter):
    """Random expand-pad (reference: detection.py:308)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _to_numpy(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            scale = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(round((scale * h * w * ratio) ** 0.5))
            nh = int(round((scale * h * w / ratio) ** 0.5))
            if nw < w or nh < h:
                continue
            x0 = pyrandom.randint(0, nw - w)
            y0 = pyrandom.randint(0, nh - h)
            canvas = onp.empty((nh, nw, 3), arr.dtype)
            canvas[:] = onp.asarray(self.pad_val, arr.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = arr
            new_label = label.copy()
            new_label[:, 1] = (label[:, 1] * w + x0) / nw
            new_label[:, 2] = (label[:, 2] * h + y0) / nh
            new_label[:, 3] = (label[:, 3] * w + x0) / nw
            new_label[:, 4] = (label[:, 4] * h + y0) / nh
            return nd.array(canvas), new_label
        return src, label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one sub-augmenter (reference: detection.py:274)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pca_noise=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Reference: detection.py:CreateDetAugmenter (same knobs/order)."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug

        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator (reference: detection.py:ImageDetIter).

    Labels come from the record header (reference pack_det format) or
    the imglist; emitted as (batch, max_objects, object_width)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_pad", "rand_gray",
                         "rand_mirror", "mean", "std", "brightness",
                         "contrast", "saturation", "hue", "pca_noise",
                         "inter_method", "min_object_covered",
                         "aspect_ratio_range", "area_range",
                         "min_eject_coverage", "max_attempts", "pad_val")})
        super().__init__(batch_size, data_shape, label_width=-1,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         path_imgidx=path_imgidx, shuffle=shuffle,
                         aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        self.det_auglist = aug_list
        self.max_objects, self.obj_width = self._infer_label_shape()

    def _parse_label(self, label):
        """Packed header label -> (num_obj, obj_width) array
        (reference: detection.py:_parse_label)."""
        raw = onp.asarray(label, "float32").reshape(-1)
        if raw.size < 2:
            raise MXNetError(f"label too short: {raw}")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        body = raw[header_width:]
        nobj = body.size // obj_width
        return body[:nobj * obj_width].reshape(nobj, obj_width)

    def _infer_label_shape(self):
        """Scan the WHOLE dataset for the max object count — a capped
        scan would silently truncate labels of late samples (reference
        detection.py estimates via label_shape/_estimate too)."""
        pos = self.cur
        maxo, width = 0, 5
        while True:
            try:
                lab, _ = self.next_sample()
            except StopIteration:
                break
            parsed = self._parse_label(lab)
            maxo = max(maxo, parsed.shape[0])
            width = parsed.shape[1]
        self.cur = pos
        self.reset()
        return max(maxo, 1), width

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size, self.max_objects,
                          self.obj_width))]

    def next(self):
        from .image import imdecode

        H, W = self.data_shape[1], self.data_shape[2]
        data = onp.zeros((self.batch_size, H, W, 3), "float32")
        labels = onp.full((self.batch_size, self.max_objects,
                           self.obj_width), -1.0, "float32")
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                lab, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            try:
                arr = imdecode(img)
            except Exception as e:  # corrupt image — skip, like reference
                import logging

                logging.debug("skipping corrupted image: %s", e)
                continue
            parsed = self._parse_label(lab)
            for aug in self.det_auglist:
                arr, parsed = aug(arr, parsed)
            a = _to_numpy(arr)
            if a.shape[:2] != (H, W):
                a = _to_numpy(imresize(a, W, H))
            data[i] = a.astype("float32")
            nobj = min(parsed.shape[0], self.max_objects)
            labels[i, :nobj] = parsed[:nobj]
            i += 1
        batch_data = nd.array(onp.transpose(data, (0, 3, 1, 2)))
        return DataBatch([batch_data], [nd.array(labels)], pad=pad)
