"""mx.image namespace (reference: python/mxnet/image/__init__.py)."""
from .image import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from . import image, detection  # noqa: F401
