"""Image IO + augmentation pipeline.

Reference: python/mxnet/image/image.py (1468 LoC; imdecode backed by
src/io/image_io.cc OpenCV kernels, ImageIter + CreateAugmenter list).
Rebuilt TPU-first: decode runs on host (native libjpeg fast path from
native/recordio.cc, PIL for other formats), augmenters are numpy-level
host transforms (they belong on host — the device pipeline starts at the
batch boundary), and the iterator emits NCHW float batches ready for a
sharded device_put.
"""
from __future__ import annotations

import io as _io
import logging
import os
import random as pyrandom

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray
from ..io.io import DataIter, DataBatch, DataDesc
from .. import recordio

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "random_size_crop",
           "color_normalize", "copyMakeBorder",
           "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
           "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "HorizontalFlipAug", "CastAug",
           "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "RandomGrayAug", "CreateAugmenter", "ImageIter"]


def _to_numpy(src):
    return src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)


def imdecode(buf, flag=1, to_rgb=1, **kwargs):
    """Decode an image byte buffer to an HWC uint8 NDArray.

    Reference: image.py:imdecode → image_io.cc Imdecode (OpenCV). Here
    PIL handles the container formats; output is RGB (to_rgb, the
    reference's default) or BGR, flag=0 → grayscale HW1."""
    from PIL import Image

    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_io.BytesIO(bytes(buf)))
    if flag == 0:
        arr = onp.asarray(img.convert("L"))[:, :, None]
    else:
        arr = onp.asarray(img.convert("RGB"))
        if not to_rgb:
            arr = arr[:, :, ::-1]
    return nd.array(onp.ascontiguousarray(arr), dtype="uint8")


def imread(filename, flag=1, to_rgb=1, **kwargs):
    """Reference: image.py:imread."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


_PIL_INTERP = None


def _interp_method(interp, sizes=()):
    """Reference interp codes (image.py:_get_interp_method): 0 nearest,
    1 bilinear, 2 bicubic, 3 area, 4 lanczos, 9 auto, 10 random."""
    global _PIL_INTERP
    from PIL import Image

    if _PIL_INTERP is None:
        R = Image.Resampling if hasattr(Image, "Resampling") else Image
        _PIL_INTERP = {0: R.NEAREST, 1: R.BILINEAR, 2: R.BICUBIC,
                       3: R.BOX, 4: R.LANCZOS}
    if interp == 9:
        if len(sizes) == 4:
            oh, ow, nh, nw = sizes
            interp = 1 if nh > oh and nw > ow else 3
        else:
            interp = 2
    elif interp == 10:
        interp = pyrandom.randint(0, 4)
    if interp not in _PIL_INTERP:
        raise MXNetError(f"unknown interp method {interp}")
    return _PIL_INTERP[interp]


def imresize(src, w, h, interp=1):
    """Reference: image.py:imresize."""
    from PIL import Image

    arr = _to_numpy(src)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    img = Image.fromarray(arr[:, :, 0] if squeeze else arr)
    method = _interp_method(interp, (arr.shape[0], arr.shape[1], h, w))
    out = onp.asarray(img.resize((w, h), method))
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=str(arr.dtype))


def resize_short(src, size, interp=2):
    """Resize so the SHORTER edge == size, preserving aspect
    (reference: image.py:resize_short)."""
    arr = _to_numpy(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(arr, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Reference: image.py:fixed_crop."""
    arr = _to_numpy(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _to_numpy(imresize(out, size[0], size[1], interp))
    return nd.array(out, dtype=str(arr.dtype))


def random_crop(src, size, interp=2):
    """Reference: image.py:random_crop → (cropped, (x0, y0, w, h))."""
    arr = _to_numpy(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Reference: image.py:center_crop."""
    arr = _to_numpy(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    """Random area+aspect crop (reference: image.py:random_size_crop)."""
    arr = _to_numpy(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(arr, size, interp)


def color_normalize(src, mean, std=None):
    """Reference: image.py:color_normalize."""
    arr = _to_numpy(src).astype("float32")
    arr = arr - onp.asarray(_to_numpy(mean), "float32")
    if std is not None:
        arr = arr / onp.asarray(_to_numpy(std), "float32")
    return nd.array(arr)


def copyMakeBorder(src, top, bot, left, right, typ=0, value=0.0):
    """Constant-border pad (reference: image_io.cc ImdecodeImpl border)."""
    arr = _to_numpy(src)
    return nd.array(onp.pad(
        arr, ((top, bot), (left, right), (0, 0)),
        mode="constant", constant_values=value).astype(arr.dtype))


# ------------------------------------------------------------ augmenters

class Augmenter:
    """Reference: image.py:Augmenter — dumps() serializes config."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                self._kwargs[k] = v.asnumpy().tolist()
            elif isinstance(v, onp.ndarray):
                self._kwargs[k] = v.tolist()

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def dumps(self):
        return [type(self).__name__, [t.dumps() for t in self.ts]]

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def dumps(self):
        return [type(self).__name__, [t.dumps() for t in self.ts]]

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2, **kwargs):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(_to_numpy(src)[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd.array(_to_numpy(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = None if mean is None else onp.asarray(
            _to_numpy(mean), "float32")
        self.std = None if std is None else onp.asarray(
            _to_numpy(std), "float32")

    def __call__(self, src):
        return color_normalize(src, self.mean if self.mean is not None
                               else 0.0, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.array(_to_numpy(src).astype("float32") * alpha)


_GRAY = onp.asarray([0.299, 0.587, 0.114], "float32")


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_numpy(src).astype("float32")
        gray = (arr * _GRAY).sum(axis=2).mean() * (1.0 - alpha)
        return nd.array(arr * alpha + gray)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_numpy(src).astype("float32")
        gray = (arr * _GRAY).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return nd.array(arr * alpha + gray)


class HueJitterAug(Augmenter):
    """YIQ-rotation hue jitter (reference: image.py:HueJitterAug, same
    tyiq/ityiq matrices)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = onp.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], "float32")
        self.ityiq = onp.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], "float32")

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = onp.cos(alpha * onp.pi)
        w = onp.sin(alpha * onp.pi)
        bt = onp.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       "float32")
        t = onp.dot(onp.dot(self.ityiq, bt), self.tyiq).T
        arr = _to_numpy(src).astype("float32")
        return nd.array(onp.dot(arr, t))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting noise (reference: image.py:LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, "float32")
        self.eigvec = onp.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, size=(3,))
        rgb = onp.dot(self.eigvec * alpha, self.eigval)
        return nd.array(_to_numpy(src).astype("float32") + rgb)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = onp.array([[0.21, 0.21, 0.21],
                              [0.72, 0.72, 0.72],
                              [0.07, 0.07, 0.07]], "float32")

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(onp.dot(
                _to_numpy(src).astype("float32"), self.mat))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference:
    image.py:CreateAugmenter — same ordering and defaults)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = onp.asarray(_to_numpy(mean))
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = onp.asarray(_to_numpy(std))
    if mean is not None or std is not None:
        if mean is not None:
            assert (mean >= 0).all()
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# -------------------------------------------------------------- ImageIter

class ImageIter(DataIter):
    """Image iterator over .rec files or image lists with augmenters.

    Reference: image.py:ImageIter (:1121). Sources: ``path_imgrec`` (the
    native-decode fast path), or ``path_imglist``/``imglist`` + files
    under ``path_root`` (PIL decode). Emits NCHW float32 batches."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", last_batch_handle="pad", **kwargs):
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(
                f"unknown last_batch_handle '{last_batch_handle}'")
        self._last_batch_handle = last_batch_handle
        self._rolled = []  # (label, raw) carried across epochs
        super().__init__(batch_size)
        assert len(data_shape) == 3 and data_shape[0] == 3, \
            "data_shape must be (3, H, W)"
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.dtype = dtype
        self._data_name = data_name
        self._label_name = label_name
        self._allow_read = True

        self.imgrec = None
        self.seq = None
        self.imglist = None
        if path_imgrec:
            self.imgrec = recordio.MXIndexedRecordIO(
                path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx",
                path_imgrec, "r") if (path_imgidx or os.path.exists(
                    os.path.splitext(path_imgrec)[0] + ".idx")) else \
                recordio.MXRecordIO(path_imgrec, "r")
            if isinstance(self.imgrec, recordio.MXIndexedRecordIO):
                self.seq = list(self.imgrec.keys)
        elif path_imglist or imglist is not None:
            self.imglist = {}
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        label = onp.array(parts[1:-1], "float32")
                        self.imglist[int(parts[0])] = (label, parts[-1])
            else:
                for i, item in enumerate(imglist):
                    label = onp.array(item[:-1], "float32").reshape(-1)
                    self.imglist[i] = (label, item[-1])
            self.seq = list(self.imglist.keys())
            self.path_root = path_root
        else:
            raise MXNetError(
                "need path_imgrec, path_imglist or imglist")
        if num_parts > 1:
            if self.seq is None:
                raise MXNetError(
                    "num_parts > 1 needs a sequence source (indexed "
                    ".rec or imglist) to partition — plain .rec without "
                    "an .idx cannot be split")
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize",
                         "rand_mirror", "mean", "std", "brightness",
                         "contrast", "saturation", "hue", "pca_noise",
                         "rand_gray", "inter_method")})
        else:
            self.auglist = aug_list
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """(label, raw image bytes or array) — reference
        image.py:next_sample."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                rec = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(rec)
                label = header.label
                return label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                return label, f.read()
        rec = self.imgrec.read()
        if rec is None:
            raise StopIteration
        header, img = recordio.unpack(rec)
        return header.label, img

    def next(self):
        H, W = self.data_shape[1], self.data_shape[2]
        data = onp.zeros((self.batch_size, H, W, 3), "float32")
        label_shape = (self.batch_size, self.label_width) if \
            self.label_width > 1 else (self.batch_size,)
        labels = onp.zeros(label_shape, "float32")
        i = 0
        pad = 0
        pending = []  # raw samples consumed into this batch
        while i < self.batch_size:
            try:
                if self._rolled:
                    lab, img = self._rolled.pop(0)
                else:
                    lab, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                if self._last_batch_handle == "discard":
                    raise
                if self._last_batch_handle == "roll_over":
                    # keep the partial batch for the next epoch
                    self._rolled = pending
                    raise
                pad = self.batch_size - i
                break
            try:
                arr = imdecode(img)
            except Exception as e:  # corrupt image — skip, like reference
                logging.debug("skipping corrupted image: %s", e)
                continue
            pending.append((lab, img))
            for aug in self.auglist:
                arr = aug(arr)
            a = _to_numpy(arr)
            if a.shape[:2] != (H, W):
                raise MXNetError(
                    f"augmented shape {a.shape} != data_shape; add a "
                    "crop/resize augmenter")
            data[i] = a.astype("float32")
            lab = onp.asarray(lab, "float32").reshape(-1)
            if self.label_width == 1:
                labels[i] = lab[0]
            else:
                labels[i, :lab.shape[0]] = lab[:self.label_width]
            i += 1
        batch_data = nd.array(
            onp.transpose(data, (0, 3, 1, 2)).astype(self.dtype))
        return DataBatch([batch_data], [nd.array(labels)], pad=pad)
