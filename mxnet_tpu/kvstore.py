"""KVStore: parameter aggregation / broadcast.

TPU-native redesign of src/kvstore/ (reference: kvstore.cc:40-73 Create,
kvstore_local.h PushImpl:206-226, comm.h CommCPU/CommDevice, kvstore_nccl.h,
kvstore_dist.h). The reference moves gradients through explicit reduce
machinery (CPU tree / GPU P2P / NCCL / ps-lite). On TPU the same user API
is kept but aggregation is executed by XLA:

- ``local`` / ``device`` — single-process aggregation: the summed reduce is
  one fused XLA add chain on device (the analog of CommDevice's NCCL-free
  reduce). With a sharded mesh, `mxnet_tpu.parallel` lowers the same
  push/pull semantics to psum over ICI inside the compiled step.
- ``dist_sync`` / ``dist_device_sync`` — multi-host: collectives over
  ICI/DCN via jax.distributed + `parallel.all_reduce` replace ps-lite
  workers/servers; `set_optimizer` (server-side update,
  kvstore_dist_server.h:346 ApplyUpdates) runs the optimizer on the
  aggregated value exactly once per key, preserving update_on_kvstore
  semantics.
"""
from __future__ import annotations

import pickle

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


class KVStore:
    """Reference: include/mxnet/kvstore.h:59-438."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._update_on_kvstore = True

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """Worker rank (reference kvstore.h:365). Multi-host: process index."""
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_index()
            except Exception:
                return 0
        return 0

    @property
    def num_workers(self):
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_count()
            except Exception:
                return 1
        return 1

    def _normalize(self, key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value]
        else:
            values = list(value)
        return keys, values, single

    def init(self, key, value):
        keys, values, _ = self._normalize(key, value)
        for k, v in zip(keys, values):
            k = str(k)
            if k in self._store:
                continue
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Aggregate (sum over the device group) then apply updater if set
        (reference: kvstore_local.h:206 PushImpl → Comm reduce → updater_)."""
        from .ndarray import sparse as _sp

        keys, values, _ = self._normalize(key, value)
        for k, v in zip(keys, values):
            k = str(k)
            if isinstance(v, (list, tuple)):
                agg = v[0]
                for x in v[1:]:
                    # sparse grads reduce sparse (reference: comm.h:478
                    # row-sparse reduce path)
                    if isinstance(agg, _sp.BaseSparseNDArray) or \
                            isinstance(x, _sp.BaseSparseNDArray):
                        agg = _sp.elemwise_add(agg, x)
                    else:
                        agg = agg + x
            else:
                agg = v
            if self._type.startswith("dist"):
                from . import parallel

                agg = parallel.all_reduce(agg)
            if k not in self._store:
                raise MXNetError(f"key {k} was not initialized")
            if self._updater is not None:
                self._updater(_key_to_int(k), agg, self._store[k])
            elif isinstance(agg, _sp.BaseSparseNDArray) or isinstance(
                    self._store[k], _sp.BaseSparseNDArray):
                # rebind wholesale: merged result may change nnz/format
                self._store[k] = _sp.elemwise_add(self._store[k], agg)
            else:
                self._store[k]._data = (self._store[k] + agg).data

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs, _ = self._normalize(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} was not initialized")
            src = self._store[k]
            from .ndarray import sparse as _sp

            if isinstance(src, _sp.BaseSparseNDArray):
                src = src.todense()
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t._data = src.data.astype(t.data.dtype)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: kvstore.h PushPull)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        keys, outs, _ = self._normalize(key, out)
        _, rids, _ = self._normalize(key, row_ids)
        for k, o, r in zip(keys, outs, rids):
            k = str(k)
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            rows = r if isinstance(r, (list, tuple)) else [r] * len(targets)
            for t, rid in zip(targets, rows):
                t._data = nd.take(src, rid, axis=0).data

    def set_updater(self, updater):
        self._updater = updater

    def set_optimizer(self, optimizer):
        """Server-side optimizer (reference: kvstore.py set_optimizer pickles
        to servers; here the updater runs on the aggregated value in-process,
        sharded across hosts by the parallel layer)."""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        self._compression = dict(compression_params)

    def barrier(self):
        """Reference: kvstore.h:391 Barrier. Multi-host: a psum sync."""
        if self._type.startswith("dist") and self.num_workers > 1:
            try:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("kvstore_barrier")
            except Exception:
                pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer is set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer is set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_to_int(k):
    try:
        return int(k)
    except ValueError:
        return k


_VALID = ("local", "device", "nccl", "dist_sync", "dist_async",
          "dist_device_sync")


def create(name="local"):
    """Reference: src/kvstore/kvstore.cc:40-73 KVStore::Create."""
    if name not in _VALID:
        raise MXNetError(f"unknown kvstore type {name}")
    return KVStore(name)
