"""KVStore: parameter aggregation / broadcast.

TPU-native redesign of src/kvstore/ (reference: kvstore.cc:40-73 Create,
kvstore_local.h PushImpl:206-226, comm.h CommCPU/CommDevice, kvstore_nccl.h,
kvstore_dist.h). The reference moves gradients through explicit reduce
machinery (CPU tree / GPU P2P / NCCL / ps-lite). On TPU the same user API
is kept but aggregation is executed by XLA:

- ``local`` / ``device`` — single-process aggregation: the summed reduce is
  one fused XLA add chain on device (the analog of CommDevice's NCCL-free
  reduce). With a sharded mesh, `mxnet_tpu.parallel` lowers the same
  push/pull semantics to psum over ICI inside the compiled step.
- ``dist_sync`` / ``dist_device_sync`` — multi-host: collectives over
  ICI/DCN via jax.distributed + `parallel.all_reduce` replace ps-lite
  workers/servers; `set_optimizer` (server-side update,
  kvstore_dist_server.h:346 ApplyUpdates) runs the optimizer on the
  aggregated value exactly once per key, preserving update_on_kvstore
  semantics.
- ``dist_async`` — push() is non-blocking: a background applier thread
  aggregates and applies updates off the critical path (the latency-
  hiding property async mode exists for; reference
  kvstore_dist_server.h async push). pull/barrier flush this worker's
  pending updates (read-your-writes); applier failures re-raise
  deferred at the next pull/barrier like the engine's poison vars.
  With >1 process it degrades to synchronous pushes — XLA collectives
  must execute in identical order on every process, which an
  independent per-worker applier thread cannot guarantee.
"""
from __future__ import annotations

import itertools
import pickle

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


class KVStore:
    """Reference: include/mxnet/kvstore.h:59-438."""

    def __init__(self, kv_type="local"):
        import os

        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._update_on_kvstore = True
        self._compression = None
        self._residuals = {}  # (key, source_idx) -> residual state
        # keys bigger than this are stored row-sharded across the local
        # device group (the analog of splitting big arrays across
        # ps-lite servers, reference kvstore_dist.h
        # MXNET_KVSTORE_BIGARRAY_BOUND)
        from . import env as _env

        self._bigarray_bound = _env.get_int(
            "MXNET_KVSTORE_BIGARRAY_BOUND", 1000000)
        # dist_async: pushes apply on a background thread (non-blocking
        # push, eventual consistency — the property async mode exists
        # for). Cross-process collectives can't be safely reordered onto
        # a worker thread (mismatched all-reduce ordering deadlocks), so
        # with >1 process async degrades to synchronous pushes.
        self._async_mode = False
        self._async_q = None
        self._async_thread = None
        self._async_err = None
        self._ps = None
        self._pipeline_async = False  # opt-in MXNET_KVSTORE_ASYNC mode

        def _nproc():
            # lazy: touching jax.process_count() initializes the jax
            # backend, which a plain local store must not force
            try:
                import jax

                return jax.process_count()
            except Exception:
                return 1

        if kv_type == "dist_async":
            nproc = _nproc()
            if nproc == 1:
                self._async_mode = True
            else:
                # multi-process: a REAL parameter server over the
                # jax.distributed coordinator KV store — pushes apply
                # individually on rank 0's applier thread, workers never
                # wait on each other (kvstore_ps.py; reference
                # kvstore_dist_server.h async mode)
                from .kvstore_ps import AsyncParamServer

                self._ps = AsyncParamServer(
                    jax.process_index(), lambda: self._updater)
        elif _env.get_bool("MXNET_KVSTORE_ASYNC", False) and (
                not kv_type.startswith("dist") or _nproc() == 1):
            # pipeline opt-in (docs/PIPELINE.md): apply LOCAL pushes on
            # the applier thread so push() returns immediately and the
            # updater overlaps the next forward. pull()/barrier() flush
            # (read-your-writes), so update_on_kvstore training loops
            # see exactly the synchronous values one step later at the
            # pull they already do. Multi-process dist types stay
            # synchronous: per-key collectives reordered onto a free
            # thread would deadlock (ordering must match across
            # workers).
            self._async_mode = True
            self._pipeline_async = True

    # -- async applier -----------------------------------------------------
    def _async_submit(self, k, agg):
        import queue
        import threading

        self._check_async_error()
        if self._async_thread is None:
            import atexit
            import weakref

            self._async_q = queue.Queue()
            ref = weakref.ref(self)

            def flush_at_exit():
                kv = ref()
                if kv is None:
                    return
                try:  # pushes after the last pull must still apply
                    kv._async_flush()
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "dist_async flush at exit failed: %s", e)

            atexit.register(flush_at_exit)
            # the worker must NOT hold a strong ref to self: a discarded
            # kvstore would otherwise be pinned (with its whole parameter
            # store) by its own applier thread forever. The weakref lets
            # the store die; its finalizer then sends the None sentinel
            # that releases the thread.
            q = self._async_q

            def drain():
                while True:
                    item = q.get()
                    try:
                        if item is None:
                            return
                        kv = ref()
                        if kv is None:
                            return
                        try:
                            kv._apply_update(*item)
                        except Exception as e:  # deferred re-raise
                            kv._async_err = kv._async_err or e
                        finally:
                            del kv
                    finally:
                        q.task_done()

            self._async_thread = threading.Thread(
                target=drain, name="kvstore-async", daemon=True)
            self._async_thread.start()
            weakref.finalize(self, q.put, None)
        self._async_q.put((k, agg))
        if self._pipeline_async:
            # count only the MXNET_KVSTORE_ASYNC opt-in — the legacy
            # dist_async mode also routes through here, and its pushes
            # must not show up as pipeline activity in the counters
            from . import pipeline as _pl

            _pl._count("kvstore_async_pushes")

    def _async_flush(self):
        """Wait for in-flight async updates; re-raise their first error
        (deferred-raise, matching the engine's poison-var semantics)."""
        if self._async_q is not None:
            self._async_q.join()
        self._check_async_error()

    def _check_async_error(self):
        if self._async_err is not None:
            err, self._async_err = self._async_err, None
            raise MXNetError(
                f"asynchronous kvstore update failed: {err}") from err

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """Worker rank (reference kvstore.h:365). Multi-host: process index."""
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_index()
            except Exception:
                return 0
        return 0

    @property
    def num_workers(self):
        if self._type.startswith("dist"):
            try:
                import jax

                return jax.process_count()
            except Exception:
                return 1
        return 1

    # itertools.count: next() is atomic under the GIL — concurrent
    # probes (monitoring thread + trainer) must never collide on the
    # same write-once key
    _dead_probe_seq = itertools.count(1)

    def num_dead_node(self, node_id=0):
        """Reference: kvstore.h:380 get_num_dead_node (ps-lite dead-node
        query). jax.distributed has no per-node heartbeat — a dead peer
        fails collectives outright — so this probes the COORDINATOR with
        a real key-value round trip: reachable cluster → 0; unreachable
        coordinator → every peer but us is unaccounted for (reference
        semantics: dead count among the queried group)."""
        if not self._type.startswith("dist"):
            return 0
        try:
            import jax

            n = jax.process_count()  # configured size (cached from init)
        except Exception:
            return 0
        if n <= 1:
            return 0
        try:  # private API: absence means "can't probe", NOT "all dead"
            from jax._src import distributed

            client = distributed.global_state.client
        except Exception:
            return 0
        if client is None:
            return 0
        try:
            # unique key per probe (set() is write-once per key), deleted
            # right after so a monitoring loop does not grow the
            # coordinator's KV store without bound
            seq = next(KVStore._dead_probe_seq)
            key = f"mxtpu/dead_probe/{self.rank}/{seq}"
            client.key_value_set(key, "1")
            try:
                client.key_value_delete(key)
            except Exception:  # graft-lint: allow(L501)
                pass  # old jax without delete: keys leak only per-probe
            return 0
        except Exception:
            # a real coordinator RPC failure: peers unaccounted for
            return max(0, n - 1)

    def _normalize(self, key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value]
        else:
            values = list(value)
        return keys, values, single

    def init(self, key, value):
        keys, values, _ = self._normalize(key, value)
        for k, v in zip(keys, values):
            k = str(k)
            if k in self._store:
                continue
            if isinstance(v, (list, tuple)):
                v = v[0]
            v = v.copy()
            self._store[k] = v
            if self._ps is not None:
                self._ps.init(k, v)
                continue
            self._maybe_shard(k)

    def _maybe_shard(self, k):
        """Row-shard big dense values across the local device group
        (reference: kvstore_dist.h big-array server split)."""
        from .ndarray import sparse as _sp

        v = self._store[k]
        if isinstance(v, _sp.BaseSparseNDArray) or not self._type.startswith(
                "dist"):
            return
        if v.size < self._bigarray_bound or v.ndim == 0:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .parallel import make_mesh

        ndev = jax.local_device_count()
        if ndev <= 1 or v.shape[0] % ndev != 0:
            return
        mesh = make_mesh({"kvshard": ndev}, devices=jax.local_devices())
        v._data = jax.device_put(v.data,
                                 NamedSharding(mesh, P("kvshard")))  # graft-lint: allow(L701)

    def _compress(self, k, idx, grad):
        """Quantize+dequantize one source's gradient through the 2-bit
        wire format with its error-feedback residual (reference:
        kvstore_dist.h PushCompressed — workers send the quantized
        tensor, the server dequantizes before aggregation)."""
        import jax.numpy as jnp

        flat = grad.data.reshape(-1).astype(jnp.float32)
        res = self._residuals.get((k, idx))
        if res is None:
            res = jnp.zeros_like(flat)
        packed, new_res = self._compression.quantize(flat, res)
        self._residuals[(k, idx)] = new_res
        deq = self._compression.dequantize(packed, flat.shape[0])
        return NDArray(deq.reshape(grad.shape).astype(grad.data.dtype))

    def push(self, key, value, priority=0):
        """Aggregate (sum over the device group) then apply updater if set
        (reference: kvstore_local.h:206 PushImpl → Comm reduce → updater_).
        A LIST value (one gradient per device) reduces in ONE compiled XLA
        all-reduce over the device group when the values live on distinct
        devices — the CommDevice/NCCL path — with serial adds as the
        same-device fallback."""
        from .ndarray import sparse as _sp
        from .resilience import faults as _faults

        # registered fault point: a lost/failed gradient send (the
        # kvstore analog of a dropped ps-lite van message)
        _faults.maybe_fail("kvstore_push")
        keys, values, _ = self._normalize(key, value)
        for k, v in zip(keys, values):
            k = str(k)
            if isinstance(v, (list, tuple)):
                vs = list(v)
                if self._compression is not None and not any(
                        isinstance(x, _sp.BaseSparseNDArray) for x in vs):
                    vs = [self._compress(k, i, x)
                          for i, x in enumerate(vs)]
                agg = None
                if len(vs) > 1 and not any(
                        isinstance(x, _sp.BaseSparseNDArray) for x in vs):
                    from . import parallel

                    try:
                        agg = parallel.group_all_reduce(vs)[0]
                    except MXNetError:
                        agg = None  # values share a device → serial sum
                if agg is None:
                    agg = vs[0]
                    for x in vs[1:]:
                        # sparse grads reduce sparse (reference:
                        # comm.h:478 row-sparse reduce path)
                        if isinstance(agg, _sp.BaseSparseNDArray) or \
                                isinstance(x, _sp.BaseSparseNDArray):
                            agg = _sp.elemwise_add(agg, x)
                        else:
                            agg = agg + x
            else:
                agg = v
                if self._compression is not None and not isinstance(
                        agg, _sp.BaseSparseNDArray):
                    agg = self._compress(k, 0, agg)
            if k not in self._store:
                raise MXNetError(f"key {k} was not initialized")
            if self._ps is not None:
                # true async: enqueue to the parameter server and return
                self._ps.push(k, agg)
                continue
            if self._async_mode:
                # dist_async: push returns immediately; a single applier
                # thread aggregates + applies off the critical path
                # (reference kvstore_dist_server.h async push handling —
                # workers never wait on each other's updates)
                self._async_submit(k, agg)
            else:
                self._apply_update(k, agg)

    def _apply_update(self, k, agg):
        from .ndarray import sparse as _sp

        if self._type.startswith("dist"):
            from . import parallel

            agg = parallel.all_reduce(agg)
        stored = self._store[k]
        if not isinstance(agg, _sp.BaseSparseNDArray) and \
                not isinstance(stored, _sp.BaseSparseNDArray) and \
                agg.data.sharding != stored.data.sharding:
            # big keys live row-sharded (_maybe_shard) — bring the
            # aggregate onto the same layout so the update stays a
            # sharded computation instead of a device clash
            import jax

            agg = NDArray(jax.device_put(agg.data,
                                         stored.data.sharding))
        if self._updater is not None:
            self._updater(_key_to_int(k), agg, stored)
        elif isinstance(agg, _sp.BaseSparseNDArray) or isinstance(
                stored, _sp.BaseSparseNDArray):
            # rebind wholesale: merged result may change nnz/format
            self._store[k] = _sp.elemwise_add(stored, agg)
        else:
            stored._data = (stored + agg).data

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Read current values. In dist_async, this worker's own pending
        pushes are flushed first (read-your-writes; the reference engine
        orders same-key push→pull through variable dependencies)."""
        from .resilience import faults as _faults

        # registered fault point: a failed parameter fetch
        _faults.maybe_fail("kvstore_pull")
        if self._async_mode:
            self._async_flush()
        keys, outs, _ = self._normalize(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} was not initialized")
            if self._ps is not None:
                # server value (read-your-writes: waits for own pushes)
                val = nd.array(self._ps.pull(k))
                self._store[k]._data = val.data.astype(
                    self._store[k].data.dtype)
            src = self._store[k]
            from .ndarray import sparse as _sp

            if isinstance(src, _sp.BaseSparseNDArray):
                src = src.todense()
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                data = src.data
                if data.sharding != t.data.sharding:
                    # don't leak the store's (possibly kvshard) layout
                    # into the caller's array
                    import jax

                    data = jax.device_put(data, t.data.sharding)
                t._data = data.astype(t.data.dtype)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: kvstore.h PushPull)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._async_mode:
            self._async_flush()
        keys, outs, _ = self._normalize(key, out)
        _, rids, _ = self._normalize(key, row_ids)
        for k, o, r in zip(keys, outs, rids):
            k = str(k)
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            rows = r if isinstance(r, (list, tuple)) else [r] * len(targets)
            for t, rid in zip(targets, rows):
                t._data = nd.take(src, rid, axis=0).data

    def set_updater(self, updater):
        self._updater = updater

    def set_optimizer(self, optimizer):
        """Server-side optimizer (reference: kvstore.py set_optimizer pickles
        to servers; here the updater runs on the aggregated value in-process,
        sharded across hosts by the parallel layer)."""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Reference: kvstore.py set_gradient_compression →
        gradient_compression.cc SetParams. 2-bit quantization with
        error-feedback residuals applies to every subsequent dense push."""
        from .gradient_compression import GradientCompression

        params = dict(compression_params)
        ctype = params.pop("type", "2bit")
        if ctype in (None, "none"):
            self._compression = None
            self._residuals.clear()
            return
        self._compression = GradientCompression(type=ctype, **params)
        self._residuals.clear()

    def barrier(self):
        """Reference: kvstore.h:391 Barrier. Multi-host: a global device
        sync; failures propagate (a swallowed barrier error would let
        workers desynchronize silently)."""
        if self._async_mode:
            self._async_flush()
        if self._ps is not None:
            # the barrier contract includes this worker's own pending
            # async pushes being durably applied
            self._ps.flush()
        if self._type.startswith("dist") and self.num_workers > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._ps is not None:
            self._ps.flush()
        if self._updater is None:
            raise MXNetError("no optimizer is set")
        if self._async_mode:
            self._async_flush()
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer is set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_to_int(k):
    try:
        return int(k)
    except ValueError:
        return k


_VALID = ("local", "device", "nccl", "dist_sync", "dist_async",
          "dist_device_sync")


def create(name="local"):
    """Reference: src/kvstore/kvstore.cc:40-73 KVStore::Create."""
    import os

    if name not in _VALID:
        raise MXNetError(f"unknown kvstore type {name}")
    kv = KVStore(name)
    from . import env as _env

    gc_type = _env.get_str("MXNET_KVSTORE_GC_TYPE")
    if gc_type:
        kv.set_gradient_compression({
            "type": gc_type,
            "threshold": _env.get_float("MXNET_KVSTORE_GC_THRESHOLD",
                                        0.5)})
    return kv
