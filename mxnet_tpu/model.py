"""Checkpoint helpers (reference: python/mxnet/model.py:394-442
save_checkpoint/load_checkpoint)."""
from __future__ import annotations

from . import ndarray as nd

__all__ = ["save_checkpoint", "load_checkpoint"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """symbol json + arg:/aux: params blob (reference: model.py:394)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    payload = {f"arg:{k}": v for k, v in arg_params.items()}
    payload.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix, epoch):
    """Reference: model.py load_checkpoint."""
    from . import symbol as sym

    symbol = sym.load(f"{prefix}-symbol.json")
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tag, name = k.split(":", 1)
        if tag == "arg":
            arg_params[name] = v
        elif tag == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params
