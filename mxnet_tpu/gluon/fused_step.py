"""Compiled fused train-step cache for the Gluon Trainer.

The eager ``Trainer.step`` hot loop is host-driven: one dispatch per
parameter for the optimizer update, a host-syncing AMP overflow check
(``LossScaler.has_overflow``), and — distributed — one collective per
parameter. This module compiles the whole weight-update phase into ONE
jit-compiled XLA executable per parameter-group signature (the
cross-replica weight-update fusion of "Automatic Cross-Replica Sharding
of Weight Update in Data-Parallel Training"; the cross-op fusion XLA is
built for). Per executable, entirely on device:

- device-side all-finite check over the raw gradients with
  ``lax.cond`` skip-step semantics — the check itself never rounds-trip
  to the host (``amp.scale_loss`` still pays ONE lazy scalar sync per
  applied step to learn the scale it must multiply the loss by —
  strictly less than the eager path's full all-finite readback);
- loss-scale grow/backoff folded into the same program (the scale,
  grow counter, skip counter and update count ride in a donated
  device-resident state tuple);
- rescale (1/batch_size · 1/loss_scale) and the multi-tensor optimizer
  update via the optimizer's ``_fused_kernel`` (optimizer/optimizer.py),
  with optimizer-state buffers donated (parameter donation is opt-in via
  ``MXNET_FUSED_STEP_DONATE`` — donation deletes the old buffer, which
  breaks tape nodes / detach() snapshots that still alias it).

Hyperparameters that change at runtime (learning rate, wd, rescale_grad,
loss scale) enter as dynamic scalar/vector arguments, so
``set_learning_rate`` and loss-scale updates never retrace. The cache is
a bounded LRU keyed like the PR-1 eager-dispatch cache: input avals +
optimizer class/static config + AMP version + distributed mode
(``MXNET_FUSED_STEP=0`` falls back to the eager per-param loop;
``MXNET_FUSED_STEP_CACHE_SIZE`` bounds the LRU). Counters surface via
``profiler.fused_step_counters()`` and the ``FUSED_STEP`` runtime
feature flag.
"""
from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp

from ..telemetry import tracer as _telem
from ..utils import compile_cache as _cc
from ..utils.lru import CountedLRUCache

__all__ = ["fused_step_enabled", "fused_step_stats",
           "reset_fused_step_cache"]


def fused_step_enabled():
    """MXNET_FUSED_STEP knob (default on); 0 = eager per-param fallback.
    Read per-step so tests/benchmarks can toggle without reimport."""
    from .. import env as _env

    return _env.get_bool("MXNET_FUSED_STEP", True)


def donate_params_enabled():
    """MXNET_FUSED_STEP_DONATE — OPT-IN (default 0) parameter-buffer
    donation. CPU/TPU donation really deletes the old buffer, which
    breaks any alias still held elsewhere (autograd tape primals for
    double-backward, detach() snapshots, user copies of ``p.data()``
    buffers). Optimizer state and the loss-scale state tuple are
    trainer-internal and always donated."""
    from .. import env as _env

    return _env.get_bool("MXNET_FUSED_STEP_DONATE", False)


class _FusedStepCache(CountedLRUCache):
    """Bounded LRU of jit-compiled fused train-step executables
    (bypasses = unsupported optimizer / sparse grads / tracers;
    fallbacks = compiled step raised and the trainer went eager)."""

    def __init__(self, maxsize=None):
        from .. import env as _env

        super().__init__(maxsize if maxsize is not None else
                         _env.get_int("MXNET_FUSED_STEP_CACHE_SIZE", 16))


_CACHE = _FusedStepCache()

# trainers holding live device step-state, for the skip-step counter
# (the count rides the donated device state tuple — no per-step host
# read — and is summed here on demand)
_TRAINERS = weakref.WeakSet()


def register_trainer(trainer):
    _TRAINERS.add(trainer)


def fused_step_stats():
    """Hit/miss/evict/bypass/fallback counters + AMP skip-step total."""
    st = _CACHE.stats()
    skipped = 0
    for tr in list(_TRAINERS):
        try:
            skipped += tr._fused_skipped_steps()
        except Exception:  # graft-lint: allow(L501)
            pass
    st["skipped_steps"] = skipped
    return st


def reset_fused_step_cache(maxsize=None):
    """Drop all cached executables and counters (tests, benchmarks)."""
    _CACHE.clear()
    if maxsize is not None:
        _CACHE.maxsize = int(maxsize)


# ---------------------------------------------------------------------------
# signatures / state pytree helpers (states are None | NDArray | nested
# tuples thereof, as built by Optimizer.create_state_multi_precision)

def state_sig(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(state_sig(x) for x in s)
    return (tuple(s.shape), str(s.data.dtype))


def state_data(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(state_data(x) for x in s)
    return s.data


def state_copy(s):
    """Device COPIES of a state tree's buffers (shape of
    ``state_data``). Snapshots that must survive a fused step need
    copies, not refs: the step donates state buffers to XLA, which
    deletes the originals."""
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(state_copy(x) for x in s)
    return jnp.array(s.data, copy=True)


def state_adopt(s):
    """Rebind a restored state tree's buffers to device-COMPUTED
    copies, in place; returns the tree.

    Restored optimizer states arrive as ``device_put`` uploads (host
    pickle -> ``nd.array``), and the fused step DONATES state buffers.
    Donating an externally-uploaded buffer is unsafe on jaxlib
    0.4.37's CPU client: the upload's storage is recycled while
    earlier computation outputs still occupy it, which surfaces as
    flaky silent corruption of unrelated live buffers on the steps
    after a ``load_states``/checkpoint restore (caught by the
    resilience bench's bitwise kill-and-resume gate). One ``jnp``
    copy makes every donated buffer an XLA computation output, which
    donates safely on every backend — restores are rare, the copy is
    device-side and cheap."""
    if s is None:
        return None
    if isinstance(s, tuple):
        for x in s:
            state_adopt(x)
        return s
    s._data = jnp.array(s.data, copy=True)
    return s


def state_tree_restore(tree):
    """The ('nd' | 'tuple' | 'raw')-tagged host state tree — the wire
    format ``Trainer.save_states`` and the resilience CheckpointManager
    both emit — rebuilt as a live NDArray state tree with donation-safe
    buffers (``state_adopt`` applied to every array leaf). ONE shared
    walk on purpose: the round-12 donation fix had to land in two
    hand-copied restore closures, which is exactly the divergence this
    helper removes."""
    from .. import ndarray as nd

    tag, val = tree
    if tag == "nd":
        return state_adopt(nd.array(val))
    if tag == "tuple":
        return tuple(state_tree_restore(s) for s in val)
    return val


def rebind_state(old, new):
    """Write the executable's output arrays back into the existing
    NDArray state objects (identity of ``trainer._states`` entries is
    preserved across steps for save_states/user references)."""
    if old is None:
        return
    if isinstance(old, tuple):
        for o, n in zip(old, new):
            rebind_state(o, n)
    else:
        old._data = new


def has_tracer(arrays):
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


# ---------------------------------------------------------------------------
# executable builder

class _FusedEntry:
    """LRU entry wrapping the fused-step executable with lazy disk-tier
    resolution. The first call (or an explicit ``prepare()``) resolves
    it: a serialized executable from a previous process is deserialized
    (no trace, no XLA compile — the warm-start win), else the jitted
    step is AOT-compiled once and written back for future processes.
    Resolution failures degrade to the plain jit path — a corrupt or
    stale cache entry must never break (or permanently eagerize) the
    trainer's step loop."""

    __slots__ = ("_jfn", "_call", "_artifact")

    def __init__(self, jfn, artifact=None):
        self._jfn = jfn
        self._call = None
        self._artifact = artifact

    def prepare(self, args):
        """Resolve without executing (``lower``/``compile`` only) —
        ``Trainer.warmup`` precompiles through this, so warmup has no
        side effects on parameters or optimizer state."""
        if self._call is None:
            self._resolve(args)

    def _resolve(self, args):
        with _telem.span("fused_step.resolve", cat="train") as sp:
            return self._resolve_inner(args, sp)

    def _resolve_inner(self, args, sp):
        art = self._artifact
        if art is not None and art.fingerprint is not None:
            loaded = art.load()
            if loaded is not None:
                sp.set(source=loaded[2])
                self._call = _cc.GuardedCompiled(loaded[0], self._jfn)
                return self._call
            try:
                with _telem.span("fused_step.trace_compile",
                                 cat="train"):
                    compiled = _cc.aot_compile(self._jfn, *args)
            except Exception:
                sp.set(source="jit_fallback")
                self._call = self._jfn
                return self._call
            sp.set(source="compile")
            art.store(compiled)
            self._call = _cc.GuardedCompiled(compiled, self._jfn)
            return self._call
        sp.set(source="jit")
        self._call = self._jfn
        return self._call

    def __call__(self, *args):
        call = self._call or self._resolve(args)
        with _telem.span("fused_step.execute", cat="train"):
            return call(*args)


def build_executable(kernel, mp_flags, scaler_cfg, donate_params,
                     cache_key=None, shard_cfg=None):
    """One donated XLA executable for the whole weight-update phase.

    kernel(w, g, s, lr, wd, rescale, t) -> (w2, s2) is the optimizer's
    fused per-parameter update (optimizer._fused_kernel), closing over
    static hyperparameters only. ``mp_flags[i]`` marks half-precision
    params updated through their fp32 master copy (state = (master,
    base)). ``scaler_cfg`` is None or (scale_factor, scale_window);
    with it the executable carries (t, scale, unskipped, skips) and
    wraps the update in ``lax.cond`` on the device-side all-finite
    check; without it the state is just (t,).

    Signature of the returned jitted function::

        step(params, grads, states, step_state, lrs, wds, rescale)
            -> (new_params, new_states, new_step_state)

    lrs/wds are f32 vectors (one per parameter, host-computed with the
    full lr_mult/wd_mult logic so multipliers never retrace); rescale is
    the f32 scalar self._scale/batch_size. States and step_state are
    donated; params donated only when ``donate_params``.

    ``shard_cfg`` (a ``sharding.FusedShardCfg``, built from the scoped
    ShardingPlan) compiles the SAME program under the mesh: params and
    grads laid out per plan, optimizer state per plan or ZeRO-1, the
    scalar step-state/hyperparameters replicated — GSPMD inserts the
    update-side collectives. Inputs not already resident at those
    layouts are resharded by jit on entry (first step after a restore);
    at steady state outputs feed back at the declared shardings and no
    data moves.
    """

    def apply_all(pvals, gvals, svals, lrs, wds, eff, t1):
        new_p, new_s = [], []
        for i, (w, g, s) in enumerate(zip(pvals, gvals, svals)):
            lr, wd = lrs[i], wds[i]
            if mp_flags[i]:
                # fp32 master update, half-precision weight written back
                # (reference: optimizer.py update_multi_precision)
                master, base = s
                m2, b2 = kernel(master, g.astype(jnp.float32), base,
                                lr, wd, eff, t1)
                new_p.append(m2.astype(w.dtype))
                new_s.append((m2, b2))
            else:
                w2, s2 = kernel(w, g, s, lr, wd, eff, t1)
                new_p.append(w2)
                new_s.append(s2)
        return tuple(new_p), tuple(new_s)

    if scaler_cfg is None:
        def step(pvals, gvals, svals, sstate, lrs, wds, rescale):
            (t,) = sstate
            t1 = t + jnp.int32(1)
            new_p, new_s = apply_all(pvals, gvals, svals, lrs, wds,
                                     rescale, t1)
            return new_p, new_s, (t1,)
    else:
        factor, window = float(scaler_cfg[0]), int(scaler_cfg[1])

        def step(pvals, gvals, svals, sstate, lrs, wds, rescale):
            t, scale, unskipped, skips = sstate
            # overflow check on the RAW (pre-rescale) gradients, exactly
            # like LossScaler.has_overflow over nd.all_finite
            finite = jnp.bool_(True)
            for g in gvals:
                if jnp.issubdtype(g.dtype, jnp.floating):
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))

            def do_apply(_):
                t1 = t + jnp.int32(1)
                # divide by the CURRENT scale (the one the loss was
                # multiplied by); powers-of-two scales make this bitwise
                # equal to the eager host-side division
                eff = rescale / scale
                new_p, new_s = apply_all(pvals, gvals, svals, lrs, wds,
                                         eff, t1)
                # grow only after the step applied (LossScaler
                # update_scale(False))
                unsk = unskipped + jnp.int32(1)
                grow = unsk >= window
                scale2 = jnp.where(grow, scale * factor, scale)
                unsk2 = jnp.where(grow, jnp.int32(0), unsk)
                return new_p, new_s, (t1, scale2, unsk2, skips)

            def do_skip(_):
                # LossScaler update_scale(True): halve (floor 1.0), and
                # leave params/states/update-count untouched
                scale2 = jnp.maximum(jnp.float32(1.0), scale / factor)
                return (tuple(pvals), tuple(svals),
                        (t, scale2, jnp.int32(0), skips + jnp.int32(1)))

            return jax.lax.cond(finite, do_apply, do_skip, None)

    donate = (0, 2, 3) if donate_params else (2, 3)
    jit_kwargs = {}
    if shard_cfg is not None:
        pshard = tuple(shard_cfg.param_shardings)
        sshard = tuple(shard_cfg.state_shardings)
        srep = tuple(shard_cfg.rep for _ in
                     range(1 if scaler_cfg is None else 4))
        rep = shard_cfg.rep
        jit_kwargs = dict(
            in_shardings=(pshard, pshard, sshard, srep, rep, rep, rep),
            out_shardings=(pshard, sshard, srep))
    # an artifact only when the disk tier is armed (MXNET_COMPILE_CACHE=0
    # must mean the plain jit path, not a no-op GuardedCompiled layer),
    # salted with the bytecode of the optimizer kernel AND this builder
    # so editing either invalidates disk entries instead of serving the
    # old update math
    from ..artifact import CompiledArtifact

    art = CompiledArtifact("fused_step", cache_key,
                           code_of=(kernel, build_executable)) \
        if cache_key is not None and _cc.cache_enabled() else None
    return _FusedEntry(
        _cc.counting_jit(step, label="fused_step", donate_argnums=donate,
                         **jit_kwargs),
        art)
