"""Recurrent cells.

TPU-native equivalent of python/mxnet/gluon/rnn/rnn_cell.py (reference:
1092 LoC — cells, Sequential/Bidirectional/Residual/Dropout/Zoneout
wrappers, unroll). The Python-loop `unroll` matches the reference API;
hybridized cells compile each step, and the fused layers (rnn_layer.py)
cover the scan-compiled path.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


class RecurrentCell(HybridBlock):
    """Reference: rnn_cell.py RecurrentCell."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if hasattr(cell, "reset"):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def state_row_shapes(self):
        """Per-state PER-ROW shapes (batch axis dropped) — the
        ``state_shapes`` a stateful serving session or
        :class:`~mxnet_tpu.serving.state.SessionStateStore` wants for
        this cell."""
        return [tuple(info["shape"][1:])
                for info in self.state_info(0)]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called "\
            "directly. Call the modifier cell instead."
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info = {k: v for k, v in info.items() if k == "shape"}
                states.append(func(**info, **kwargs))
            else:
                states.append(func(shape=(0,), **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Reference: rnn_cell.py unroll."""
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, nd.NDArray):
            batch_size = inputs.shape[batch_axis]
            inputs = nd.split(inputs, num_outputs=length, axis=axis,
                              squeeze_axis=True)
            if length == 1:
                inputs = [inputs] if isinstance(inputs, nd.NDArray) \
                    else list(inputs)
            else:
                inputs = list(inputs)
        else:
            batch_size = inputs[0].shape[batch_axis - 1 if batch_axis > axis
                                         else batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=axis)
            stacked = nd.sequence_mask(
                nd.swapaxes(stacked, 0, axis) if axis != 0 else stacked,
                sequence_length=valid_length, use_sequence_length=True)
            if axis != 0:
                stacked = nd.swapaxes(stacked, 0, axis)
            outputs = stacked
            if merge_outputs is False:
                outputs = [o.squeeze(axis=axis)
                           for o in nd.split(outputs, length, axis=axis)]
        elif merge_outputs or merge_outputs is None and False:
            outputs = nd.stack(*outputs, axis=axis)
        elif merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        if merge_outputs and not isinstance(outputs, nd.NDArray):
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, x, states):
        self._counter += 1
        return super().forward(x, states)


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    """Elman cell (reference: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.fully_connected(inputs, i2h_weight, i2h_bias,
                                num_hidden=self._hidden_size)
        h2h = F.fully_connected(states[0], h2h_weight, h2h_bias,
                                num_hidden=self._hidden_size)
        output = F.activation(i2h + h2h, act_type=self._activation) \
            if self._activation in ("relu", "tanh", "sigmoid", "softrelu") \
            else getattr(F, self._activation)(i2h + h2h)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """Reference: rnn_cell.py LSTMCell (gate order i,f,g,o — cuDNN compat)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.fully_connected(inputs, i2h_weight, i2h_bias,
                                num_hidden=4 * self._hidden_size)
        h2h = F.fully_connected(states[0], h2h_weight, h2h_bias,
                                num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        in_gate, forget_gate, in_trans, out_gate = \
            F.split(gates, num_outputs=4, axis=-1)
        in_gate = F.sigmoid(in_gate)
        forget_gate = F.sigmoid(forget_gate)
        in_trans = F.tanh(in_trans)
        out_gate = F.sigmoid(out_gate)
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """Reference: rnn_cell.py GRUCell (gate order r,z,n — cuDNN compat)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.fully_connected(inputs, i2h_weight, i2h_bias,
                                num_hidden=3 * self._hidden_size)
        h2h = F.fully_connected(prev_state_h, h2h_weight, h2h_bias,
                                num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=-1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h + reset_gate * h2h)
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Reference: rnn_cell.py SequentialRNNCell."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """Reference: rnn_cell.py DropoutCell."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + "modifier_")
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func or nd.zeros, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Reference: rnn_cell.py ZoneoutCell."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        mask = lambda p, like: F.dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(self.zoneout_outputs, next_output), next_output,
                         prev_output) if self.zoneout_outputs > 0. \
            else next_output
        new_states = [F.where(mask(self.zoneout_states, ns), ns, s)
                      for ns, s in zip(next_states, states)] \
            if self.zoneout_states > 0. else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Reference: rnn_cell.py ResidualCell."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """Reference: rnn_cell.py BidirectionalCell."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, nd.NDArray):
            seq = [s for s in nd.split(inputs, length, axis=axis,
                                       squeeze_axis=True)] if length > 1 else \
                [inputs.squeeze(axis=axis)]
        else:
            seq = list(inputs)
        l_cell, r_cell = self._children.values()
        batch_size = seq[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        n_l = len(l_cell.state_info())
        l_outputs, l_states = l_cell.unroll(
            length, seq, begin_state[:n_l], layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, list(reversed(seq)), begin_state[n_l:], layout,
            merge_outputs=False)
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
