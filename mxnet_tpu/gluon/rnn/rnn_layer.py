"""Fused recurrent layers.

TPU-native equivalent of python/mxnet/gluon/rnn/rnn_layer.py (reference:
RNN/LSTM/GRU over the fused RNN op; cuDNN path rnn-inl.h:447). Parameters
are kept as per-layer/direction i2h/h2h weights+biases with the reference's
names (l0_i2h_weight, r0_h2h_bias, ...) for checkpoint compatibility, and
packed into the fused op's cuDNN-layout vector at forward time (a free
concat under jit). The time loop is a lax.scan inside the op.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][:self._dir]:
                    self._register_param(f"{j}{i}_i2h_weight",
                                         (ng * nh, ni if i == 0 else
                                          nh * self._dir),
                                         i2h_weight_initializer)
                    self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                         h2h_weight_initializer)
                    self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                         i2h_bias_initializer)
                    self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                         h2h_bias_initializer)

    def _register_param(self, name, shape, init):
        if self._input_size == 0 and "i2h_weight" in name and \
                name.startswith(("l0", "r0")):
            shape = (shape[0], 0)
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def infer_param_shapes(self, x, *args):
        ni = x.shape[-1]
        self._input_size = ni
        for j in ["l", "r"][:self._dir]:
            p = getattr(self, f"{j}0_i2h_weight")
            p.shape = (self._gates * self._hidden_size, ni)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference: rnn_layer.py begin_state)."""
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(info["shape"], **kwargs))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, dtype=inputs.data.dtype
                                      if hasattr(inputs, "data") else "float32")
        if isinstance(states, nd.NDArray):
            states = [states]
        # pack parameters in cuDNN order: all weights, then all biases
        weights, biases = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                weights.append(params[f"{j}{i}_i2h_weight"].reshape((-1,)))
                weights.append(params[f"{j}{i}_h2h_weight"].reshape((-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                biases.append(params[f"{j}{i}_i2h_bias"])
                biases.append(params[f"{j}{i}_h2h_bias"])
        packed = F.concat(*(weights + biases), dim=0)
        out = F.rnn(inputs, packed, states[0],
                    states[1] if self._mode == "lstm" else None,
                    state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        outputs, out_states = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, 0, 1)
        if skip_states:
            return outputs
        return outputs, out_states


class RNN(_RNNLayer):
    """Reference: rnn_layer.py RNN (vanilla Elman, relu/tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Reference: rnn_layer.py LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", projection_size,
                         **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Reference: rnn_layer.py GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
