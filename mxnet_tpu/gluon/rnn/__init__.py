"""Gluon recurrent layers (reference: python/mxnet/gluon/rnn/)."""
from .rnn_layer import RNN, LSTM, GRU
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, BidirectionalCell, DropoutCell,
                       ZoneoutCell, ResidualCell, HybridRecurrentCell)

__all__ = ["RNN", "LSTM", "GRU", "RecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell", "HybridRecurrentCell"]
