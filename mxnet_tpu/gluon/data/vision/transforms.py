"""Vision transforms (reference:
python/mxnet/gluon/data/vision/transforms.py). Operate on HWC uint8/float
NDArrays like the reference; heavy augmentation runs as registered image
ops so it can execute on device when fused into the input pipeline.
"""
from __future__ import annotations

import numpy as onp

from .... import ndarray as nd
from ....ndarray import NDArray
from ....ndarray import ops_image as _ops_image
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomLighting",
           "RandomHue", "RandomColorJitter", "CropResize"]


class Compose(Sequential):
    """Reference: transforms.py Compose."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference: image/to_tensor)."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype=onp.float32).reshape(-1, 1, 1)
        self._std = onp.asarray(std, dtype=onp.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        return (x - nd.array(self._mean)) / nd.array(self._std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        import jax.image

        h, w = self._size[1], self._size[0]
        if x.ndim == 3:
            out = jax.image.resize(x.data.astype("float32"),
                                   (h, w, x.shape[2]), method="linear")
        else:
            out = jax.image.resize(x.data.astype("float32"),
                                   (x.shape[0], h, w, x.shape[3]),
                                   method="linear")
        return nd.from_jax(out.astype(x.data.dtype))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0, x0 = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    """Reference: transforms.py RandomResizedCrop."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import math
        import random as pyrandom

        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target_area = pyrandom.uniform(*self._scale) * area
            log_ratio = (math.log(self._ratio[0]), math.log(self._ratio[1]))
            aspect = math.exp(pyrandom.uniform(*log_ratio))
            w = int(round(math.sqrt(target_area * aspect)))
            h = int(round(math.sqrt(target_area / aspect)))
            if 0 < w <= W and 0 < h <= H:
                x0 = pyrandom.randint(0, W - w)
                y0 = pyrandom.randint(0, H - h)
                crop = x[y0:y0 + h, x0:x0 + w, :]
                return Resize(self._size)(crop)
        return Resize(self._size)(CenterCrop(min(H, W))(x))


class _RandomFlip(Block):
    _axis = 1

    def forward(self, x):
        import random as pyrandom

        if pyrandom.random() < 0.5:
            return nd.flip(x, axis=self._axis)
        return x


class RandomFlipLeftRight(_RandomFlip):
    _axis = 1


class RandomFlipTopBottom(_RandomFlip):
    _axis = 0


class _RandomJitter(Block):
    """Host-drawn alpha + the shared jitter math from ops_image (one
    source of truth for the BT.601 / YIQ constants and blend formulas —
    the registered `nd.image.random_*` ops use the same helpers with
    device-side draws)."""

    _impl = None  # staticmethod(jnp_array, alpha) -> jnp_array

    def __init__(self, val):
        super().__init__()
        self._val = val

    def _alpha(self):
        import random as pyrandom

        return 1.0 + pyrandom.uniform(-self._val, self._val)

    def forward(self, x):
        f = x.astype("float32")
        out = NDArray(type(self)._impl(f.data, self._alpha()))
        return nd.clip(out, 0, 255).astype(x.dtype) if x.dtype == onp.uint8 \
            else out


class RandomBrightness(_RandomJitter):
    _impl = staticmethod(_ops_image._brightness)


class RandomContrast(_RandomJitter):
    _impl = staticmethod(_ops_image._contrast)


class RandomSaturation(_RandomJitter):
    _impl = staticmethod(_ops_image._saturation)


class RandomHue(_RandomJitter):
    """YIQ-rotation hue jitter (reference: transforms.py RandomHue /
    image.py HueJitterAug matrices; math in ops_image._hue)."""

    _impl = staticmethod(_ops_image._hue)

    def _alpha(self):
        import random as pyrandom

        return pyrandom.uniform(-self._val, self._val)  # rotation, not 1+u


class RandomColorJitter(Block):
    """Brightness/contrast/saturation/hue jitter in one transform
    (reference: transforms.py RandomColorJitter — applies each enabled
    jitter in random order)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        import random as pyrandom

        ts = list(self._ts)
        pyrandom.shuffle(ts)
        for t in ts:
            x = t(x)
        return x


class CropResize(Block):
    """Fixed crop then optional resize (reference: transforms.py
    CropResize(x, y, width, height, size, interpolation))."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._x, self._y = int(x), int(y)
        self._w, self._h = int(width), int(height)
        self._size = size
        self._interp = interpolation

    def forward(self, data):
        # (..., H, W, C): support batched input like CenterCrop
        H, W = data.shape[-3], data.shape[-2]
        if self._y + self._h > H or self._x + self._w > W:
            raise ValueError(
                f"crop ({self._x},{self._y},{self._w},{self._h}) exceeds "
                f"image size {W}x{H}")
        out = data[..., self._y:self._y + self._h,
                   self._x:self._x + self._w, :]
        if self._size is not None:
            from ....image import imresize

            size = self._size if isinstance(self._size, (list, tuple)) \
                else (self._size, self._size)
            if out.ndim == 3:
                out = imresize(out, size[0], size[1],
                               interp=self._interp)
            else:
                from ... import nd as _nd

                out = _nd.stack(*[imresize(out[i], size[0], size[1],
                                           interp=self._interp)
                                  for i in range(out.shape[0])], axis=0)
        return out


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference: transforms.py RandomLighting;
    eigen-basis shared with ops_image._adjust)."""

    def __init__(self, alpha_std=0.05):
        super().__init__()
        self._alpha_std = alpha_std

    def forward(self, x):
        alpha = onp.random.normal(0, self._alpha_std, 3).astype(onp.float32)
        f = NDArray(_ops_image._adjust(x.astype("float32").data, alpha))
        return nd.clip(f, 0, 255).astype(x.dtype) if x.dtype == onp.uint8 \
            else f
