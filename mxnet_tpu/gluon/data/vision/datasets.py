"""Vision datasets.

Reference: python/mxnet/gluon/data/vision/datasets.py (MNIST/FashionMNIST/
CIFAR10/CIFAR100/ImageRecordDataset/ImageFolderDataset). This environment
has no network egress: datasets read standard local files when present
(idx-ubyte for MNIST, python pickles for CIFAR) and otherwise synthesize
deterministic random data of the right shape so pipelines/tests run
hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from .... import ndarray as nd
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        ndim = magic[2]
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(shape)


class MNIST(_DownloadedDataset):
    """Reference: datasets.py MNIST."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
    _synth_n = 512

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        img_f, lbl_f = self._train_files if self._train else self._test_files
        img_path = os.path.join(self._root, img_f)
        lbl_path = os.path.join(self._root, lbl_f)
        found = None
        for suffix in ("", ".gz"):
            if os.path.exists(img_path + suffix):
                found = suffix
                break
        if found is not None:
            data = _read_idx(img_path + found).reshape(-1, 28, 28, 1)
            label = _read_idx(lbl_path + found).astype(onp.int32)
        else:
            rng = onp.random.RandomState(42 if self._train else 7)
            data = (rng.rand(self._synth_n, 28, 28, 1) * 255).astype(onp.uint8)
            label = rng.randint(0, 10, self._synth_n).astype(onp.int32)
        self._data = nd.array(data, dtype=onp.uint8)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """Reference: datasets.py CIFAR10 (python pickle batches)."""

    _synth_n = 512
    _nclass = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        import pickle

        files = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        paths = [os.path.join(self._root, "cifar-10-batches-py", f)
                 for f in files]
        if all(os.path.exists(p) for p in paths):
            datas, labels = [], []
            for p in paths:
                with open(p, "rb") as f:
                    batch = pickle.load(f, encoding="latin1")
                datas.append(onp.asarray(batch["data"]).reshape(
                    -1, 3, 32, 32).transpose(0, 2, 3, 1))
                labels.extend(batch["labels" if "labels" in batch
                                    else "fine_labels"])
            data = onp.concatenate(datas).astype(onp.uint8)
            label = onp.asarray(labels, dtype=onp.int32)
        else:
            rng = onp.random.RandomState(13 if self._train else 31)
            data = (rng.rand(self._synth_n, 32, 32, 3) * 255).astype(onp.uint8)
            label = rng.randint(0, self._nclass, self._synth_n).astype(onp.int32)
        self._data = nd.array(data, dtype=onp.uint8)
        self._label = label


class CIFAR100(CIFAR10):
    _nclass = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"), fine_label=False,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)


class ImageFolderDataset(Dataset):
    """Reference: datasets.py ImageFolderDataset (one folder per class)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from .... import image

        img = image.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(Dataset):
    """Reference: datasets.py ImageRecordDataset over .rec files."""

    def __init__(self, filename, flag=1, transform=None):
        from .... import recordio

        self._flag = flag
        self._transform = transform
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        from .... import image, recordio

        record = self._record.read_idx(self._record.keys[idx])
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record.keys)
