"""Vision datasets + transforms (reference:
python/mxnet/gluon/data/vision/)."""
from .datasets import MNIST, FashionMNIST, CIFAR10, CIFAR100, \
    ImageFolderDataset, ImageRecordDataset
from . import transforms

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset", "transforms"]
