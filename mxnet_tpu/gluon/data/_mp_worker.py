"""Cross-process DataLoader workers with shared-memory batch transfer.

Reference: python/mxnet/gluon/data/dataloader.py:28-156 — fork-based
worker pool whose NDArray pickling rides POSIX shm (ForkingPickler +
reduce_ndarray). TPU-native constraint: an initialized XLA runtime must
NOT be forked, so workers use the 'spawn' context with a one-time
initializer (CPU-only JAX in children), and batches come back as
(shm_name, shape, dtype) descriptors over multiprocessing.shared_memory
— the same zero-copy-on-transfer idea as the reference's shm NDArrays
without ever pickling tensor bytes through a pipe.
"""
from __future__ import annotations

import os
from multiprocessing import shared_memory

import numpy as onp

_WORKER_DATASET = None
_WORKER_BATCHIFY = None


def _init_worker(dataset, batchify_fn):
    """Spawn-context initializer: runs once per worker process BEFORE
    any jax use, pinning the child to CPU so worker processes never
    fight over the TPU."""
    global _WORKER_DATASET, _WORKER_BATCHIFY
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _WORKER_DATASET = dataset
    _WORKER_BATCHIFY = batchify_fn


def _to_shm(arr):
    """numpy array -> (shm_name, shape, dtype); child leaks the handle
    on purpose — the parent owns unlink."""
    arr = onp.ascontiguousarray(arr)
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    view = onp.ndarray(arr.shape, arr.dtype, buffer=shm.buf)
    view[:] = arr
    name = shm.name
    shm.close()
    return (name, arr.shape, str(arr.dtype))


def _from_shm(desc):
    name, shape, dtype = desc
    shm = shared_memory.SharedMemory(name=name)
    arr = onp.ndarray(shape, onp.dtype(dtype), buffer=shm.buf).copy()
    shm.close()
    shm.unlink()
    return arr


def _encode(obj):
    """Replace numpy/NDArray leaves of a batch structure with shm
    descriptors."""
    if hasattr(obj, "asnumpy"):
        return ("__shm__", _to_shm(obj.asnumpy()))
    if isinstance(obj, onp.ndarray):
        return ("__shm__", _to_shm(obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_encode(x) for x in obj)
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    return obj


def decode(obj):
    """Parent side: shm descriptors -> NDArray leaves."""
    from ... import ndarray as nd

    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__shm__":
        return nd.array(_from_shm(obj[1]))
    if isinstance(obj, (list, tuple)):
        return type(obj)(decode(x) for x in obj)
    if isinstance(obj, dict):
        return {k: decode(v) for k, v in obj.items()}
    return obj


def worker_make_batch(indices):
    """Runs in the worker: fetch samples, batchify, export via shm."""
    batch = _WORKER_BATCHIFY([_WORKER_DATASET[i] for i in indices])
    return _encode(batch)
