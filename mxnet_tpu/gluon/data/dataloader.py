"""DataLoader.

TPU-native equivalent of python/mxnet/gluon/data/dataloader.py (reference:
DataLoader with multiprocessing workers + shared-memory NDArray pickling
:28-156, worker_loop :207). On TPU hosts the loader uses a thread pool:
decode/augment releases the GIL inside numpy/PIL, and batches transfer to
HBM asynchronously, which fills the same role as the reference's fork-based
workers + CPUSharedStorageManager without cross-process NDArray plumbing.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from concurrent.futures.process import BrokenProcessPool

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return nd.array(data, dtype=data.dtype)


class DataLoader:
    """Reference: dataloader.py DataLoader.

    ``prefetch`` is the worker-pool pipeline depth: how many batches may
    be in flight (submitted to workers, not yet consumed) ahead of the
    consumer. ``None`` (default) reads ``MXNET_DATALOADER_PREFETCH``,
    falling back to ``2 * num_workers``; an explicit value always wins,
    and is clamped to >= 1 whenever workers are on (depth 0 would
    deadlock the pipelined iterator). Only meaningful with
    ``num_workers > 0`` — the synchronous loader has no pipeline.

    ``timeout`` (seconds, reference dataloader.py default 120) bounds
    the wait on any single worker batch: a worker stuck longer (hung
    decode, dead process) raises RuntimeError in the consumer instead
    of hanging the epoch; ``<= 0`` or ``None`` waits forever.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=None, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120):
        # num_workers: None (default) falls back to MXNET_MP_WORKER_NTHREADS;
        # an EXPLICIT 0 stays synchronous regardless of the env var
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = None if timeout is None or timeout <= 0 \
            else float(timeout)
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        if num_workers is None:
            from ... import env as _env

            num_workers = _env.get_int("MXNET_MP_WORKER_NTHREADS", 0)
        self._num_workers = num_workers
        if prefetch is None:
            from ... import env as _env

            prefetch = _env.get_int("MXNET_DATALOADER_PREFETCH",
                                    2 * max(num_workers, 1))
        self._prefetch = max(0, int(prefetch))
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._decode = None
        if num_workers > 0 and not thread_pool:
            # cross-process workers (reference dataloader.py:207 worker
            # pool + shm NDArray transfer). forkserver context: workers
            # fork from a clean server process that never initialized
            # XLA (forking a live XLA runtime is unsafe) and — unlike
            # spawn — never re-imports __main__, so guard-less scripts
            # and REPLs work
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            from . import _mp_worker

            try:
                ctx = multiprocessing.get_context("forkserver")
            except ValueError:  # platform without forkserver
                ctx = multiprocessing.get_context("spawn")
            self._pool = ProcessPoolExecutor(
                max_workers=num_workers, mp_context=ctx,
                initializer=_mp_worker._init_worker,
                initargs=(self._dataset, self._batchify_fn))
            self._decode = _mp_worker.decode
            self._submit_fn = _mp_worker.worker_make_batch
        elif num_workers > 0:
            self._pool = ThreadPoolExecutor(
                max_workers=max(num_workers, 1))
            self._submit_fn = self._make_batch
        else:
            self._pool = None

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._pool is None:
            for batch_indices in self._batch_sampler:
                yield self._make_batch(batch_indices)
            return
        # pipelined prefetch through the worker pool; RESULT COLLECTION
        # (future wait + decode) is an engine op on the IO lane, like
        # the reference's PrefetcherIter hand-off — NaiveEngine makes it
        # inline-synchronous, poison carries worker errors to the wait.
        # Futures are submitted EAGERLY and independently of the engine,
        # so worker parallelism survives even an inline engine.
        from ... import engine as _engine

        eng = _engine.get()
        depth = max(1, self._prefetch)
        slot_vars = self._checkout_vars(eng, depth)
        # under an inline engine the collect op blocks at push — defer
        # it to emit time so `depth` worker futures stay in flight
        defer_collect = isinstance(eng, _engine.NaiveEngine)
        slots = [None] * depth
        pending = []  # (fut, slot) submitted but collect not yet pushed
        it = iter(self._batch_sampler)
        state = {"submitted": 0}

        def push_collect(fut, slot):
            def collect(fut=fut, slot=slot):
                try:
                    b = fut.result(timeout=self._timeout)
                except FuturesTimeoutError:
                    fut.cancel()
                    raise RuntimeError(
                        f"DataLoader worker batch took longer than "
                        f"timeout={self._timeout}s (hung decode or dead "
                        "worker); raise the timeout= constructor "
                        "argument for slow datasets") from None
                if self._decode is not None:
                    b = self._decode(b)
                slots[slot] = b

            eng.push(collect, mutable_vars=(slot_vars[slot],),
                     lane=_engine.LANE_IO)

        def submit_next():
            try:
                indices = list(next(it))
            except StopIteration:
                return False
            fut = self._pool.submit(self._submit_fn, indices)
            slot = state["submitted"] % depth
            state["submitted"] += 1
            if defer_collect:
                pending.append((fut, slot))
            else:
                push_collect(fut, slot)
            return True

        for _ in range(depth):
            if not submit_next():
                break
        emitted = 0
        clean = True
        try:
            while emitted < state["submitted"]:
                slot = emitted % depth
                if defer_collect and pending and pending[0][1] == slot:
                    push_collect(*pending.pop(0))
                try:
                    eng.wait_for_var(slot_vars[slot])
                except BrokenProcessPool:
                    clean = False
                    raise RuntimeError(
                        "DataLoader process workers died during startup. "
                        "Like torch's DataLoader, process workers need "
                        "the script's entry point guarded with "
                        "`if __name__ == '__main__':` (spawn/forkserver "
                        "re-import __main__); alternatively pass "
                        "thread_pool=True for guard-free thread workers."
                    ) from None
                except BaseException:
                    clean = False
                    raise
                batch = slots[slot]
                slots[slot] = None
                emitted += 1
                submit_next()
                yield batch
        finally:
            # clean vars go back to the instance pool (bounded var
            # table). An abandoned iterator (consumer break) may still
            # have collect ops in flight — drain them first so a late
            # failure can't poison a var AFTER it was pooled
            if clean:
                for v in slot_vars:
                    try:
                        eng.wait_for_var(v)
                    except BaseException:
                        clean = False
            if clean:
                self._return_vars(eng, slot_vars)

    def _checkout_vars(self, eng, depth):
        """Per-instance var pool: concurrent iterators get distinct var
        lists; sequential epochs reuse them instead of growing the
        engine's var table forever."""
        pool = getattr(self, "_var_pool", None)
        if pool is None:
            pool = self._var_pool = []
        while pool:
            e, vs = pool.pop()
            if e is eng and len(vs) == depth:
                return vs
        return [eng.new_variable() for _ in range(depth)]

    def _return_vars(self, eng, vs):
        self._var_pool.append((eng, vs))

    def __len__(self):
        return len(self._batch_sampler)
