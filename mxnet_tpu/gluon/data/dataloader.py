"""DataLoader.

TPU-native equivalent of python/mxnet/gluon/data/dataloader.py (reference:
DataLoader with multiprocessing workers + shared-memory NDArray pickling
:28-156, worker_loop :207). On TPU hosts the loader uses a thread pool:
decode/augment releases the GIL inside numpy/PIL, and batches transfer to
HBM asynchronously, which fills the same role as the reference's fork-based
workers + CPUSharedStorageManager without cross-process NDArray plumbing.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from concurrent.futures.process import BrokenProcessPool

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return nd.array(data, dtype=data.dtype)


class DataLoader:
    """Reference: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=None, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120):
        # num_workers: None (default) falls back to MXNET_MP_WORKER_NTHREADS;
        # an EXPLICIT 0 stays synchronous regardless of the env var
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        if num_workers is None:
            from ... import env as _env

            num_workers = _env.get_int("MXNET_MP_WORKER_NTHREADS", 0)
        self._num_workers = num_workers
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * max(num_workers, 1))
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._decode = None
        if num_workers > 0 and not thread_pool:
            # cross-process workers (reference dataloader.py:207 worker
            # pool + shm NDArray transfer). forkserver context: workers
            # fork from a clean server process that never initialized
            # XLA (forking a live XLA runtime is unsafe) and — unlike
            # spawn — never re-imports __main__, so guard-less scripts
            # and REPLs work
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            from . import _mp_worker

            try:
                ctx = multiprocessing.get_context("forkserver")
            except ValueError:  # platform without forkserver
                ctx = multiprocessing.get_context("spawn")
            self._pool = ProcessPoolExecutor(
                max_workers=num_workers, mp_context=ctx,
                initializer=_mp_worker._init_worker,
                initargs=(self._dataset, self._batchify_fn))
            self._decode = _mp_worker.decode
            self._submit_fn = _mp_worker.worker_make_batch
        elif num_workers > 0:
            self._pool = ThreadPoolExecutor(
                max_workers=max(num_workers, 1))
            self._submit_fn = self._make_batch
        else:
            self._pool = None

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._pool is None:
            for batch_indices in self._batch_sampler:
                yield self._make_batch(batch_indices)
            return
        # pipelined prefetch through the worker pool (threads or
        # processes — same schedule)
        futures = []
        it = iter(self._batch_sampler)
        try:
            for _ in range(self._prefetch):
                futures.append(self._pool.submit(self._submit_fn,
                                                 list(next(it))))
        except StopIteration:
            pass
        while futures:
            try:
                batch = futures.pop(0).result()
            except BrokenProcessPool:
                raise RuntimeError(
                    "DataLoader process workers died during startup. "
                    "Like torch's DataLoader, process workers need the "
                    "script's entry point guarded with "
                    "`if __name__ == '__main__':` (spawn/forkserver "
                    "re-import __main__); alternatively pass "
                    "thread_pool=True for guard-free thread workers."
                ) from None
            if self._decode is not None:
                batch = self._decode(batch)
            try:
                futures.append(self._pool.submit(self._submit_fn,
                                                 list(next(it))))
            except StopIteration:
                pass
            yield batch

    def __len__(self):
        return len(self._batch_sampler)
