"""ResNet V1/V2 (reference: python/mxnet/gluon/model_zoo/vision/resnet.py).

The flagship model family for the TPU build: all convs lower to XLA
conv_general_dilated tiled onto the MXU; BN folds into the surrounding
fusion. Blocks default to NCHW for API parity with the reference zoo;
pass layout="NHWC" (a TPU-native extension) to keep channels in XLA's
preferred minor dimension end-to-end (convs, BN axis, pooling).
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (HybridSequential, Conv2D, BatchNorm, Activation, Dense,
                   GlobalAvgPool2D, MaxPool2D, Flatten)

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                  use_bias=False, in_channels=in_channels, layout=layout)


def _bn_axis(layout):
    return -1 if layout == "NHWC" else 1


def _stem_conv(channels, stem_s2d, **kw):
    """The full-size stem: plain 7x7/2 conv, or its space-to-depth
    equivalent when stem_s2d is set."""
    return _S2DStemConv(channels, **kw) if stem_s2d \
        else Conv2D(channels, 7, 2, 3, **kw)


class _S2DStemConv(Conv2D):
    """The stem 7x7/2 conv computed as a 4x4/1 conv over a 2x
    space-to-depth input — bit-equivalent, but MXU-friendly: the MXU
    tiles poorly on a 3-channel stride-2 conv (3/128 lanes busy), while
    the s2d form feeds 12 channels with unit stride (the MLPerf-ResNet
    TPU stem). Parameters are IDENTICAL to the plain Conv2D (same name,
    shape, checkpoint bytes); the reshuffle is recomputed inside the
    step, where XLA folds it.

    Derivation: o(i,j) = sum_{u,v<7} w[u,v] x[2i+u-3, 2j+v-3]. Substitute
    h = 2I + r (r the parity): with w padded by one leading zero to 8 and
    split as u+1 = 2q + r, the sum becomes a 4-tap unit-stride conv over
    the (I, r)-packed input with asymmetric pad (2, 1) — implemented as
    pad-by-(4, 2) in the original resolution.
    """

    def __init__(self, channels, layout="NCHW", **kwargs):
        super().__init__(channels, 7, 2, 3, layout=layout, **kwargs)

    def hybrid_forward(self, F, x, weight, bias=None):
        from .... import symbol as _sym

        if isinstance(x, _sym.Symbol):
            # F=sym trace (export/ONNX): symbols carry no static shape for
            # the packing reshapes — emit the equivalent plain 7x7/2 conv
            return super().hybrid_forward(F, x, weight, bias)
        nhwc = self._channel_last
        O = self._channels
        if nhwc:
            N, H, W, C = x.shape
        else:
            N, C, H, W = x.shape
        # left pad 4 always; right pad rounds the padded size up to even
        # so odd inputs (which the plain 7x7/2 conv accepts) still pack —
        # output stays ceil(H/2), matching the plain conv
        rh, rw = 2 + (H % 2), 2 + (W % 2)
        Ip, Jp = (H + 4 + rh) // 2, (W + 4 + rw) // 2
        if nhwc:
            x = F.pad(x, mode="constant",
                      pad_width=(0, 0, 4, rh, 4, rw, 0, 0))
            xs = F.reshape(x, (N, Ip, 2, Jp, 2, C))
            xs = F.transpose(xs, axes=(0, 1, 3, 5, 2, 4))
            xs = F.reshape(xs, (N, Ip, Jp, C * 4))
            w = F.transpose(weight, axes=(0, 3, 1, 2))  # (O,C,7,7)
        else:
            x = F.pad(x, mode="constant",
                      pad_width=(0, 0, 0, 0, 4, rh, 4, rw))
            xs = F.reshape(x, (N, C, Ip, 2, Jp, 2))
            xs = F.transpose(xs, axes=(0, 1, 3, 5, 2, 4))
            xs = F.reshape(xs, (N, C * 4, Ip, Jp))
            w = weight
        # one leading zero makes kernel index u+1 = 2q + r split cleanly
        w = F.pad(w, mode="constant", pad_width=(0, 0, 0, 0, 1, 0, 1, 0))
        w = F.reshape(w, (O, C, 4, 2, 4, 2))
        w = F.transpose(w, axes=(0, 1, 3, 5, 2, 4))  # (O,C,ry,rx,qy,qx)
        w = F.reshape(w, (O, C * 4, 4, 4))
        if nhwc:
            w = F.transpose(w, axes=(0, 2, 3, 1))  # (O,4,4,C*4)
        out = F.convolution(xs, w, bias, kernel=(4, 4), stride=(1, 1),
                            dilate=(1, 1), pad=(0, 0), num_filter=O,
                            no_bias=bias is None, layout=self._layout)
        if self.act is not None:
            out = self.act(out)
        return out


def _make_norm(ax, norm_layer=None, norm_kwargs=None, **extra):
    """Instantiate a block's norm layer: BatchNorm by default; pass
    norm_layer=gluon.contrib.nn.SyncBatchNorm (+ norm_kwargs) for
    cross-device batch stats under SPMD training."""
    kw = dict(norm_kwargs or {})
    kw.setdefault("axis", ax)
    kw.update(extra)
    return (norm_layer or BatchNorm)(**kw)


class BasicBlockV1(HybridBlock):
    """Reference: resnet.py BasicBlockV1."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", norm_layer=None, norm_kwargs=None, **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        norm = lambda **extra: _make_norm(ax, norm_layer, norm_kwargs,
                                          **extra)
        self.body = HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(norm())
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(norm())
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1,
                                       strides=stride, use_bias=False,
                                       in_channels=in_channels,
                                       layout=layout))
            self.downsample.add(norm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    """Reference: resnet.py BottleneckV1."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", norm_layer=None, norm_kwargs=None, **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        norm = lambda **extra: _make_norm(ax, norm_layer, norm_kwargs,
                                          **extra)
        self.body = HybridSequential(prefix="")
        self.body.add(Conv2D(channels // 4, kernel_size=1, strides=stride,
                             layout=layout))
        self.body.add(norm())
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(norm())
        self.body.add(Activation("relu"))
        self.body.add(Conv2D(channels, kernel_size=1, strides=1,
                             layout=layout))
        self.body.add(norm())
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1,
                                       strides=stride, use_bias=False,
                                       in_channels=in_channels,
                                       layout=layout))
            self.downsample.add(norm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    """Reference: resnet.py BasicBlockV2 (pre-activation)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", norm_layer=None, norm_kwargs=None, **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = _make_norm(ax, norm_layer, norm_kwargs)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = _make_norm(ax, norm_layer, norm_kwargs)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = Conv2D(channels, 1, stride, use_bias=False,
                                     in_channels=in_channels,
                                     layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """Reference: resnet.py BottleneckV2."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", norm_layer=None, norm_kwargs=None, **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = _make_norm(ax, norm_layer, norm_kwargs)
        self.conv1 = Conv2D(channels // 4, kernel_size=1, strides=1,
                            use_bias=False, layout=layout)
        self.bn2 = _make_norm(ax, norm_layer, norm_kwargs)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = _make_norm(ax, norm_layer, norm_kwargs)
        self.conv3 = Conv2D(channels, kernel_size=1, strides=1,
                            use_bias=False, layout=layout)
        if downsample:
            self.downsample = Conv2D(channels, 1, stride, use_bias=False,
                                     in_channels=in_channels,
                                     layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """Reference: resnet.py ResNetV1."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", norm_layer=None, norm_kwargs=None,
                 stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        assert layout in ("NCHW", "NHWC"), layout
        self._layout = layout
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                self.features.add(_stem_conv(channels[0], stem_s2d,
                                             use_bias=False, layout=layout))
                self.features.add(_make_norm(ax, norm_layer, norm_kwargs))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1, layout=layout))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i], layout=layout,
                    norm_layer=norm_layer, norm_kwargs=norm_kwargs))
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.output = Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW", norm_layer=None,
                    norm_kwargs=None):
        layer = HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout,
                            norm_layer=norm_layer, norm_kwargs=norm_kwargs,
                            prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, norm_layer=norm_layer,
                                norm_kwargs=norm_kwargs, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    """Reference: resnet.py ResNetV2."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", norm_layer=None, norm_kwargs=None,
                 stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        assert layout in ("NCHW", "NHWC"), layout
        self._layout = layout
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            self.features.add(_make_norm(ax, norm_layer, norm_kwargs,
                                         scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                self.features.add(_stem_conv(channels[0], stem_s2d,
                                             use_bias=False, layout=layout))
                self.features.add(_make_norm(ax, norm_layer, norm_kwargs))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1, layout=layout))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels, layout=layout,
                    norm_layer=norm_layer, norm_kwargs=norm_kwargs))
                in_channels = channels[i + 1]
            self.features.add(_make_norm(ax, norm_layer, norm_kwargs))
            self.features.add(Activation("relu"))
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.features.add(Flatten())
            self.output = Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
               34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
               50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
               101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
               152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048])}

resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [{"basic_block": BasicBlockV1,
                          "bottle_neck": BottleneckV1},
                         {"basic_block": BasicBlockV2,
                          "bottle_neck": BottleneckV2}]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """Reference: resnet.py get_resnet."""
    assert num_layers in resnet_spec, \
        f"Invalid number of layers: {num_layers}. " \
        f"Options are {str(resnet_spec.keys())}"
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2, \
        f"Invalid resnet version: {version}. Options are 1 and 2."
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(get_model_file(
            f"resnet{num_layers}_v{version}", root=root))
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
