"""SqueezeNet 1.0/1.1 (reference:
python/mxnet/gluon/model_zoo/vision/squeezenet.py, Iandola et al. 2016)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (HybridSequential, Conv2D, MaxPool2D, AvgPool2D, Dropout,
                   Activation, Flatten)

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = HybridSequential(prefix="")
    out.add(_make_fire_conv(squeeze_channels, 1))

    from ...contrib.nn import HybridConcurrent
    paths = HybridConcurrent(axis=1, prefix="")
    paths.add(_make_fire_conv(expand1x1_channels, 1))
    paths.add(_make_fire_conv(expand3x3_channels, 3, 1))
    out.add(paths)
    return out


def _make_fire_conv(channels, kernel_size, padding=0):
    out = HybridSequential(prefix="")
    out.add(Conv2D(channels, kernel_size, padding=padding))
    out.add(Activation("relu"))
    return out


class SqueezeNet(HybridBlock):
    """version '1.0' or '1.1' (1.1 moves pools earlier: ~2.4x less compute
    at equal accuracy)."""

    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1"), \
            "Unsupported SqueezeNet version {}".format(version)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(Conv2D(96, kernel_size=7, strides=2))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(pool_size=3, strides=2,
                                            ceil_mode=True))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(MaxPool2D(pool_size=3, strides=2,
                                            ceil_mode=True))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(64, 256, 256))
                self.features.add(MaxPool2D(pool_size=3, strides=2,
                                            ceil_mode=True))
                self.features.add(_make_fire(64, 256, 256))
            else:
                self.features.add(Conv2D(64, kernel_size=3, strides=2))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(pool_size=3, strides=2,
                                            ceil_mode=True))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(MaxPool2D(pool_size=3, strides=2,
                                            ceil_mode=True))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(MaxPool2D(pool_size=3, strides=2,
                                            ceil_mode=True))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(64, 256, 256))
                self.features.add(_make_fire(64, 256, 256))
            self.features.add(Dropout(0.5))

            self.output = HybridSequential(prefix="")
            self.output.add(Conv2D(classes, kernel_size=1))
            self.output.add(Activation("relu"))
            self.output.add(AvgPool2D(13))
            self.output.add(Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def get_squeezenet(version, pretrained=False, ctx=None, root=None,
                   **kwargs):
    """Reference: squeezenet.py get_squeezenet."""
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        net.load_parameters(
            get_model_file(f"squeezenet{version}", root=root))
    return net


def squeezenet1_0(**kwargs):
    return get_squeezenet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet("1.1", **kwargs)
