"""MobileNet v1 (Howard et al. 2017) and v2 (Sandler et al. 2018).

Reference: python/mxnet/gluon/model_zoo/vision/mobilenet.py. Depthwise
convs lower to XLA grouped conv_general_dilated (feature_group_count=C);
the 1x1 pointwise convs are where the MXU FLOPs live.
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (HybridSequential, Conv2D, BatchNorm, Activation,
                   GlobalAvgPool2D, Flatten, Dense)

__all__ = ["MobileNet", "MobileNetV2",
           "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
           "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
           "mobilenet_v2_0_25",
           "get_mobilenet", "get_mobilenet_v2"]


class RELU6(HybridBlock):
    """Reference: mobilenet.py RELU6 (clip(x, 0, 6))."""

    def hybrid_forward(self, F, x):
        return F.clip(x, 0, 6)


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False, layout="NCHW"):
    out.add(Conv2D(channels, kernel, stride, pad, groups=num_group,
                   use_bias=False, layout=layout))
    from .resnet import _bn_axis

    out.add(BatchNorm(scale=True, axis=_bn_axis(layout)))
    if active:
        out.add(RELU6() if relu6 else Activation("relu"))


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False,
                 layout="NCHW"):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6, layout=layout)
    _add_conv(out, channels, relu6=relu6, layout=layout)


class LinearBottleneck(HybridBlock):
    """MobileNetV2 inverted residual (reference: mobilenet.py
    LinearBottleneck): 1x1 expand (t*) → 3x3 depthwise → 1x1 linear
    project, residual add when stride==1 and channels match."""

    def __init__(self, in_channels, channels, t, stride, layout="NCHW",
                 **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = HybridSequential()
            _add_conv(self.out, in_channels * t, relu6=True,
                      layout=layout)
            _add_conv(self.out, in_channels * t, kernel=3, stride=stride,
                      pad=1, num_group=in_channels * t, relu6=True,
                      layout=layout)
            _add_conv(self.out, channels, active=False, relu6=True,
                      layout=layout)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    """MobileNet v1 with width multiplier (reference: mobilenet.py
    MobileNet)."""

    def __init__(self, multiplier=1.0, classes=1000, layout="NCHW",
                 **kwargs):
        super().__init__(**kwargs)
        assert layout in ("NCHW", "NHWC"), layout
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            _add_conv(self.features, channels=int(32 * multiplier),
                      kernel=3, pad=1, stride=2, layout=layout)
            dw_channels = [int(x * multiplier) for x in
                           [32, 64] + [128] * 2 + [256] * 2 + [512] * 6
                           + [1024]]
            channels = [int(x * multiplier) for x in
                        [64] + [128] * 2 + [256] * 2 + [512] * 6
                        + [1024] * 2]
            strides = [1, 2] * 3 + [1] * 5 + [2, 1]
            for dwc, c, s in zip(dw_channels, channels, strides):
                _add_conv_dw(self.features, dw_channels=dwc, channels=c,
                             stride=s, layout=layout)
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.features.add(Flatten())
            self.output = Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class MobileNetV2(HybridBlock):
    """MobileNet v2 (reference: mobilenet.py MobileNetV2)."""

    def __init__(self, multiplier=1.0, classes=1000, layout="NCHW",
                 **kwargs):
        super().__init__(**kwargs)
        assert layout in ("NCHW", "NHWC"), layout
        with self.name_scope():
            self.features = HybridSequential(prefix="features_")
            _add_conv(self.features, int(32 * multiplier), kernel=3,
                      stride=2, pad=1, relu6=True, layout=layout)

            in_channels_group = [int(x * multiplier) for x in
                                 [32] + [16] + [24] * 2 + [32] * 3
                                 + [64] * 4 + [96] * 3 + [160] * 3]
            channels_group = [int(x * multiplier) for x in
                              [16] + [24] * 2 + [32] * 3 + [64] * 4
                              + [96] * 3 + [160] * 3 + [320]]
            ts = [1] + [6] * 16
            strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3

            for in_c, c, t, s in zip(in_channels_group, channels_group,
                                     ts, strides):
                self.features.add(LinearBottleneck(in_channels=in_c,
                                                   channels=c, t=t,
                                                   stride=s,
                                                   layout=layout))

            last_channels = int(1280 * multiplier) if multiplier > 1.0 \
                else 1280
            _add_conv(self.features, last_channels, relu6=True,
                      layout=layout)
            self.features.add(GlobalAvgPool2D(layout=layout))

            self.output = HybridSequential(prefix="output_")
            self.output.add(Conv2D(classes, 1, use_bias=False,
                                   prefix="pred_", layout=layout))
            self.output.add(Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def get_mobilenet(multiplier, pretrained=False, ctx=None, root=None,
                  **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        ver = str(float(multiplier))
        net.load_parameters(
            get_model_file(f"mobilenet{ver}", root=root))
    return net


def get_mobilenet_v2(multiplier, pretrained=False, ctx=None, root=None,
                     **kwargs):
    net = MobileNetV2(multiplier, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        ver = str(float(multiplier))
        net.load_parameters(
            get_model_file(f"mobilenetv2_{ver}", root=root))
    return net


def mobilenet1_0(**kwargs):
    return get_mobilenet(1.0, **kwargs)


def mobilenet0_75(**kwargs):
    return get_mobilenet(0.75, **kwargs)


def mobilenet0_5(**kwargs):
    return get_mobilenet(0.5, **kwargs)


def mobilenet0_25(**kwargs):
    return get_mobilenet(0.25, **kwargs)


def mobilenet_v2_1_0(**kwargs):
    return get_mobilenet_v2(1.0, **kwargs)


def mobilenet_v2_0_75(**kwargs):
    return get_mobilenet_v2(0.75, **kwargs)


def mobilenet_v2_0_5(**kwargs):
    return get_mobilenet_v2(0.5, **kwargs)


def mobilenet_v2_0_25(**kwargs):
    return get_mobilenet_v2(0.25, **kwargs)
