"""Vision model zoo (reference:
python/mxnet/gluon/model_zoo/vision/__init__.py get_model:91)."""
from . import resnet as _resnet
from . import alexnet as _alexnet
from . import vgg as _vgg
from . import squeezenet as _squeezenet
from . import mobilenet as _mobilenet
from . import densenet as _densenet
from . import inception as _inception

from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403

_models = {}
for _mod in (_resnet, _alexnet, _vgg, _squeezenet, _mobilenet, _densenet,
             _inception):
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower() and \
                not _name.startswith("get_"):
            _models[_name] = _obj


def get_model(name, **kwargs):
    """Reference: vision/__init__.py:91."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"Model {name} is not supported. Available: {sorted(_models)}")
    return _models[name](**kwargs)
