"""Vision model zoo (reference:
python/mxnet/gluon/model_zoo/vision/__init__.py get_model:91)."""
from . import resnet as _resnet
from . import alexnet as _alexnet

from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403

_models = {}
for _mod in (_resnet, _alexnet):
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower():
            _models[_name] = _obj


def get_model(name, **kwargs):
    """Reference: vision/__init__.py:91."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"Model {name} is not supported. Available: {sorted(_models)}")
    return _models[name](**kwargs)
