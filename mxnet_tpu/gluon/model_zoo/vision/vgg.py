"""VGG models (reference: python/mxnet/gluon/model_zoo/vision/vgg.py,
Simonyan & Zisserman 2014)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (HybridSequential, Conv2D, Dense, Dropout, BatchNorm,
                   MaxPool2D, Flatten, Activation)
from ....initializer import Xavier

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "get_vgg"]


class VGG(HybridBlock):
    """layers/filters spec per stage; conv3x3 stacks + maxpool, then the
    classic 4096-4096-classes head."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(Dense(4096, activation="relu",
                                    weight_initializer="normal"))
            self.features.add(Dropout(rate=0.5))
            self.features.add(Dense(4096, activation="relu",
                                    weight_initializer="normal"))
            self.features.add(Dropout(rate=0.5))
            self.output = Dense(classes, weight_initializer="normal")

    def _make_features(self, layers, filters, batch_norm):
        featurizer = HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(Conv2D(filters[i], kernel_size=3, padding=1,
                                      weight_initializer=Xavier(
                                          rnd_type="gaussian",
                                          factor_type="out", magnitude=2)))
                if batch_norm:
                    featurizer.add(BatchNorm())
                featurizer.add(Activation("relu"))
            featurizer.add(MaxPool2D(strides=2))
        featurizer.add(Flatten())
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    """Reference: vgg.py get_vgg."""
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import get_model_file

        bn = "_bn" if kwargs.get("batch_norm") else ""
        net.load_parameters(
            get_model_file(f"vgg{num_layers}{bn}", root=root))
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(11, **kwargs)


def vgg13_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(13, **kwargs)


def vgg16_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(16, **kwargs)


def vgg19_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(19, **kwargs)
