"""Pretrained-weight store: local cache + checksum-verified fetch.

Reference: python/mxnet/gluon/model_zoo/model_store.py. Same cache
layout (``$MXNET_HOME/models/<name>-<shorthash>.params``) and the same
published checksum table, so reference-era downloaded weights drop in
unchanged. In an air-gapped environment (no egress) ``get_model_file``
resolves purely from the cache and raises a clear error telling the
user where to place the file otherwise.
"""
from __future__ import annotations

import hashlib
import os
import zipfile

__all__ = ["get_model_file", "purge"]

# published (sha1, name) table — interop data matching the reference's
# released weight files (model_store.py:29)
_model_sha1 = {name: checksum for checksum, name in [
    ("44335d1f0046b328243b32a26a4fbd62d9057b45", "alexnet"),
    ("f27dbf2dbd5ce9a80b102d89c7483342cd33cb31", "densenet121"),
    ("b6c8a95717e3e761bd88d145f4d0a214aaa515dc", "densenet161"),
    ("2603f878403c6aa5a71a124c4a3307143d6820e9", "densenet169"),
    ("1cdbc116bc3a1b65832b18cf53e1cb8e7da017eb", "densenet201"),
    ("ed47ec45a937b656fcc94dabde85495bbef5ba1f", "inceptionv3"),
    ("9f83e440996887baf91a6aff1cccc1c903a64274", "mobilenet0.25"),
    ("8e9d539cc66aa5efa71c4b6af983b936ab8701c3", "mobilenet0.5"),
    ("529b2c7f4934e6cb851155b22c96c9ab0a7c4dc2", "mobilenet0.75"),
    ("6b8c5106c730e8750bcd82ceb75220a3351157cd", "mobilenet1.0"),
    ("36da4ff1867abccd32b29592d79fc753bca5a215", "mobilenetv2_1.0"),
    ("e2be7b72a79fe4a750d1dd415afedf01c3ea818d", "mobilenetv2_0.75"),
    ("aabd26cd335379fcb72ae6c8fac45a70eab11785", "mobilenetv2_0.5"),
    ("ae8f9392789b04822cbb1d98c27283fc5f8aa0a7", "mobilenetv2_0.25"),
    ("a0666292f0a30ff61f857b0b66efc0228eb6a54b", "resnet18_v1"),
    ("48216ba99a8b1005d75c0f3a0c422301a0473233", "resnet34_v1"),
    ("0aee57f96768c0a2d5b23a6ec91eb08dfb0a45ce", "resnet50_v1"),
    ("d988c13d6159779e907140a638c56f229634cb02", "resnet101_v1"),
    ("671c637a14387ab9e2654eafd0d493d86b1c8579", "resnet152_v1"),
    ("a81db45fd7b7a2d12ab97cd88ef0a5ac48b8f657", "resnet18_v2"),
    ("9d6b80bbc35169de6b6edecffdd6047c56fdd322", "resnet34_v2"),
    ("ecdde35339c1aadbec4f547857078e734a76fb49", "resnet50_v2"),
    ("18e93e4f48947e002547f50eabbcc9c83e516aa6", "resnet101_v2"),
    ("f2695542de38cf7e71ed58f02893d82bb409415e", "resnet152_v2"),
    ("264ba4970a0cc87a4f15c96e25246a1307caf523", "squeezenet1.0"),
    ("33ba0f93753c83d86e1eb397f38a667eaf2e9376", "squeezenet1.1"),
    ("dd221b160977f36a53f464cb54648d227c707a05", "vgg11"),
    ("ee79a8098a91fbe05b7a973fed2017a6117723a8", "vgg11_bn"),
    ("6bc5de58a05a5e2e7f493e2d75a580d83efde38c", "vgg13"),
    ("7d97a06c3c7a1aecc88b6e7385c2b373a249e95e", "vgg13_bn"),
    ("e660d4569ccb679ec68f1fd3cce07a387252a90a", "vgg16"),
    ("7f01cf050d357127a73826045c245041b0df7363", "vgg16_bn"),
    ("ad2f660d101905472b83590b59708b71ea22b2e5", "vgg19"),
    ("f360b758e856f1074a85abd5fd873ed1d98297c3", "vgg19_bn"),
]}

apache_repo_url = \
    "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/"
_url_format = "{repo_url}gluon/models/{file_name}.zip"


def data_dir():
    from ... import env as _env

    return _env.get_str(
        "MXNET_HOME", os.path.join(os.path.expanduser("~"), ".mxnet"))


def short_hash(name):
    if name not in _model_sha1:
        raise ValueError(f"Pretrained model for {name} is not available.")
    return _model_sha1[name][:8]


def check_sha1(filename, sha1_hash):
    """True iff the file's sha1 matches (reference: gluon/utils.py)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def get_model_file(name, root=None):
    """Resolve (and checksum-verify) a pretrained weight file from the
    local cache, downloading if the environment allows egress.

    Reference: model_store.py:get_model_file — same resolution order.
    """
    root = os.path.expanduser(root or os.path.join(data_dir(), "models"))
    file_name = f"{name}-{short_hash(name)}"
    file_path = os.path.join(root, file_name + ".params")
    sha1_hash = _model_sha1[name]
    if os.path.exists(file_path):
        if check_sha1(file_path, sha1_hash):
            return file_path
        print(f"Mismatch in the content of model file {file_path} "
              "detected. Downloading again.")
    os.makedirs(root, exist_ok=True)
    zip_path = os.path.join(root, file_name + ".zip")
    from ... import env as _env

    url = _url_format.format(repo_url=_env.get_str(
        "MXNET_GLUON_REPO", apache_repo_url), file_name=file_name)
    try:
        import urllib.request

        urllib.request.urlretrieve(url, zip_path)
    except Exception as e:
        raise RuntimeError(
            f"cannot download pretrained weights for '{name}' "
            f"({e}); this environment has no egress — place the file "
            f"at {file_path} (sha1 {sha1_hash}) manually") from e
    with zipfile.ZipFile(zip_path) as zf:
        zf.extractall(root)
    os.remove(zip_path)
    if check_sha1(file_path, sha1_hash):
        return file_path
    raise ValueError(
        f"Downloaded file for {name} has a mismatched sha1; "
        "the repo may be outdated or the download corrupted")


def purge(root=None):
    """Delete cached pretrained files (reference: model_store.py:purge)."""
    root = os.path.expanduser(root or os.path.join(data_dir(), "models"))
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
