"""Gluon imperative API (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock, CachedOp
from . import nn
from . import loss
from .trainer import Trainer
from . import utils
from . import data
from . import rnn
from . import model_zoo

__all__ = ["Parameter", "Constant", "ParameterDict",
           "DeferredInitializationError", "Block", "HybridBlock",
           "SymbolBlock", "CachedOp", "nn", "loss", "Trainer", "utils",
           "data", "rnn", "model_zoo"]
