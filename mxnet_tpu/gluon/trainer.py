"""Gluon Trainer.

TPU-native equivalent of python/mxnet/gluon/trainer.py (reference:
Trainer:27, kvstore wiring :169-217, step/allreduce_grads/update). The
reference pushes grads through kvstore (CPU/GPU reduce or ps-lite); here
single-host aggregation is implicit (one logical grad per param) and
multi-host runs ride `mxnet_tpu.parallel` collectives.

``step`` runs through the compiled fused train-step by default
(gluon/fused_step.py): ONE jit-compiled, buffer-donated XLA executable
per parameter-group signature covering the bucketed gradient allreduce,
the device-side AMP overflow check with ``lax.cond`` skip-step
semantics, rescale, and the multi-tensor optimizer update — the analog
of the reference's multi-tensor fused update ops
(src/operator/contrib/preloaded_multi_sgd.cc) extended to the whole
weight-update phase. ``MXNET_FUSED_STEP=0``, optimizers without a fused
kernel, and sparse gradients fall back to the eager per-param loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from . import fused_step as _fs
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contexts = None
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore if isinstance(kvstore, str) else \
            getattr(kvstore, "type", "device")
        self._kvstore = kvstore if isinstance(kvstore, kvs.KVStore) else None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._distributed = self._kvstore_type.startswith("dist")
        self._states_created = False
        self._fused = None           # cached (key, executable) for this trainer
        self._fused_state = None     # device-resident (t[, scale, unsk, skips])
        self._fused_broken = False   # compiled step raised once; stay eager
        self._fused_skips_host = 0   # skip total carried across re-seeds
        self._grad_reducer = None    # dispatch-as-ready bucketed allreduce

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _create_states(self):
        self._states = [
            self._optimizer.create_state_multi_precision(i, p.data())
            for i, p in enumerate(self._params)]
        self._states_created = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        """Takes effect on the very next step: the fused executable reads
        lr as a dynamic scalar argument, so no recompilation happens."""
        self._optimizer.set_learning_rate(lr)

    def allreduce_grads(self):
        """Cross-worker gradient all-reduce (reference: trainer.py
        _allreduce_grads via kvstore push/pull). Single host: no-op (one
        logical grad); dist: dense gradients are coalesced into
        dtype-bucketed flattened collectives
        (parallel.all_reduce_coalesced) instead of one psum per
        parameter; sparse gradients keep the per-tensor path.

        With ``MXNET_ASYNC_GRAD_SYNC`` (default on) the dense buckets
        are dispatched AS BACKWARD PRODUCES THEM via the grad-ready
        hook (pipeline/grad_sync.py) — this call then only flushes the
        partial buckets and binds the already-reduced results, so the
        collectives overlap the backward instead of serializing after
        it. Values are bit-identical on both paths."""
        if not self._distributed:
            return
        from .. import parallel
        from ..ndarray import sparse as _sp

        grads = [p.grad() for p in self._params if p.grad_req != "null"]
        dense = [g for g in grads
                 if not isinstance(g, _sp.BaseSparseNDArray)]
        reducer = self._async_reducer()
        if dense and reducer is not None:
            reducer.flush(dense)
        elif dense:
            for g, r in zip(dense, parallel.all_reduce_coalesced(dense)):
                g._data = r.data
        for g in grads:
            if isinstance(g, _sp.BaseSparseNDArray):
                g._data = parallel.all_reduce(g).data

    def _async_reducer(self):
        """The dispatch-as-ready bucketed reducer, created and hooked
        into autograd once per trainer while MXNET_ASYNC_GRAD_SYNC is
        on (the hook itself no-ops per round when toggled off, so the
        knob stays a pure fallback switch)."""
        from .. import pipeline as _pl

        if not _pl.async_grad_sync_enabled():
            if self._grad_reducer is not None:
                # knob flipped off between backward and step: discard
                # this round's speculation and re-arm the hook's
                # per-round knob read, else it keeps dispatching
                self._grad_reducer.abandon()
            return None
        if self._grad_reducer is None:
            self._grad_reducer = _pl.AsyncGradReducer(
                self._params).attach()
        return self._grad_reducer

    def _abandon_speculation(self):
        """Discard any in-flight MXNET_ASYNC_GRAD_SYNC speculation
        (pending buckets + speculative reductions) without binding it.
        State capture/restore boundaries — ``save_states``,
        ``load_states``, CheckpointManager snapshots — must call this:
        a speculative reduction captured before the boundary would
        otherwise be bound into the first step AFTER it, mixing
        pre-restore gradient values into post-restore math."""
        if self._grad_reducer is not None:
            self._grad_reducer.abandon()

    # -- fused compiled step ------------------------------------------------

    def _fused_skipped_steps(self):
        """AMP skip-step total (host carry + live device counter)."""
        st = self._fused_state
        if st is not None and len(st["vals"]) == 4:
            return int(st["vals"][3])
        return self._fused_skips_host

    def _invalidate_fused_state(self):
        st = self._fused_state
        if st is not None and len(st["vals"]) == 4:
            try:
                self._fused_skips_host = int(st["vals"][3])
            except Exception:  # graft-lint: allow(L501)
                # the state tuple was donated to an executable that then
                # failed at execution — the buffers are gone; keep the
                # last host carry rather than crash the eager fallback
                pass
        self._fused_state = None

    def _sync_fused_state(self):
        """Pull the device-resident step state back into the host
        mirrors: optimizer.num_update (authoritative update count — the
        host mirror drifts by the number of AMP-skipped steps) and the
        loss scaler's scale/window counter. Called by save_states and by
        ``LossScaler.loss_scale`` property reads; a no-op unless a fused
        step ran since the last sync, so repeated reads (one
        ``amp.scale_loss`` per iteration) cost at most one scalar
        device read per step."""
        st = self._fused_state
        if st is None or not st.get("dirty", True):
            return
        vals = st["vals"]
        t = int(vals[0])
        self._optimizer.num_update = t
        for k in self._optimizer._index_update_count:
            self._optimizer._index_update_count[k] = t
        st["expected_num_update"] = t
        if len(vals) == 4:
            scaler = getattr(self, "_amp_loss_scaler", None)
            if scaler is not None:
                scaler._loss_scale = float(vals[1])
                scaler._unskipped = int(vals[2])
                st["scaler_mirror"] = (scaler._loss_scale,
                                       scaler._unskipped)
            self._fused_skips_host = int(vals[3])
        st["dirty"] = False

    def _ensure_fused_state(self, scaler):
        """(Re)seed the donated device step-state when absent or when the
        host-side sources changed externally (load_states, a user write
        to scaler.loss_scale / optimizer.num_update)."""
        optim = self._optimizer
        st = self._fused_state
        mode = 4 if scaler is not None else 1
        if st is not None and len(st["vals"]) == mode:
            if st["expected_num_update"] == optim.num_update and (
                    scaler is None or st["scaler_mirror"] ==
                    (scaler._loss_scale, scaler._unskipped)):
                return st
        self._invalidate_fused_state()
        vals = (jnp.int32(optim.num_update),)
        mirror = None
        if scaler is not None:
            vals = vals + (jnp.float32(scaler._loss_scale),
                           jnp.int32(scaler._unskipped),
                           jnp.int32(self._fused_skips_host))
            mirror = (scaler._loss_scale, scaler._unskipped)
            scaler._device_sync = self._sync_fused_state
        st = {"vals": vals, "expected_num_update": optim.num_update,
              "scaler_mirror": mirror, "dirty": True}
        self._fused_state = st
        _fs.register_trainer(self)
        return st

    def _fused_step(self, batch_size, scaler):
        """One compiled-executable step; False = bypass to the eager
        path (unsupported optimizer, sparse grads, tracers). The full
        aval signature / LRU key is only rebuilt when cheap identity
        tokens change (param buffers rebound by cast(), states replaced
        by load_states, grad_req edits, hyperparameter statics) — the
        steady-state per-step host work is gathering buffers and the
        dynamic lr/wd/rescale scalars. A stale token is a perf miss, not
        a correctness hazard: the inner jax.jit re-specializes on avals
        anyway."""
        from ..ndarray import sparse as _sp

        optim = self._optimizer
        kern = optim._fused_kernel()
        if kern is None:
            _fs._CACHE.note_bypass()
            return False
        if not self._states_created:
            self._create_states()
        kernel_key, kernel = kern
        scaler_cfg = None if scaler is None else \
            (float(scaler._scale_factor), int(scaler._scale_window))
        donate_params = _fs.donate_params_enabled()
        from ..ndarray import registry as _registry

        token = (kernel_key, scaler_cfg, donate_params,
                 _registry.amp_version(), self._shard_token(),
                 tuple(p._grad_req for p in self._params))
        cache = self._fused
        if cache is not None and cache["token"] == token and \
                cache["states"] is self._states and \
                cache["nd_ids"] == tuple(
                    (id(p._ndarray), id(p._ndarray._grad))
                    for p in cache["params"]):
            params, grads = cache["params"], cache["grads"]
            states, entry = cache["work_states"], cache["entry"]
            if any(isinstance(g, _sp.BaseSparseNDArray) for g in grads) \
                    or _fs.has_tracer([g.data for g in grads]):
                _fs._CACHE.note_bypass()
                return False
            _fs._CACHE.note_hit()
        else:
            group = self._fused_group(kernel_key, scaler_cfg,
                                      donate_params)
            if group == "empty":
                return True  # nothing to update; eager loop no-ops too
            if group is None:
                _fs._CACHE.note_bypass()
                return False
            work, params = group["work"], group["params"]
            grads, states = group["grads"], group["states"]
            entry = self._fused_entry(group, kernel, scaler_cfg,
                                      donate_params)
            self._fused = cache = {
                "token": token, "states": self._states,
                "nd_ids": tuple((id(p._ndarray), id(p._ndarray._grad))
                                for p in params),
                "params": params, "grads": grads, "work_states": states,
                "work": work, "entry": entry,
                "shard_cfg": group.get("shard_cfg"),
                "lr_host": None, "lr_dev": None,
                "wd_host": None, "wd_dev": None,
                "rescale_host": None, "rescale_dev": None}
        work = cache["work"]
        if donate_params:
            # MXNET_GRAPH_VERIFY-gated: donating parameter buffers while
            # a tape node still holds them as saved primals means the
            # next backward reads deleted memory (analysis/donation.py).
            # Checked before the host count mirror advances so an
            # =error raise leaves the optimizer state untouched.
            from ..analysis import check_param_donation

            check_param_donation(
                [(p.name, p._ndarray._data) for p in params])
        st = self._ensure_fused_state(scaler)

        # host update-count mirror advances like the eager path (on AMP
        # overflow the device t stays put and the mirror drifts until
        # _sync_fused_state); lr/wd computed AFTER the bump so an
        # attached lr_scheduler sees the same num_update as eager
        snap = (optim.num_update, dict(optim._index_update_count))
        for i in work:
            optim._update_count(i)
        lr_host = [optim._get_lr(i) for i in work]
        if lr_host != cache["lr_host"]:
            cache["lr_host"] = lr_host
            cache["lr_dev"] = jnp.asarray(lr_host, jnp.float32)
        lrs = cache["lr_dev"]
        wd_host = [optim._get_wd(i) for i in work]
        if wd_host != cache["wd_host"]:
            cache["wd_host"] = wd_host
            cache["wd_dev"] = jnp.asarray(wd_host, jnp.float32)
        wds = cache["wd_dev"]
        rescale_host = self._scale / batch_size
        if rescale_host != cache["rescale_host"]:
            cache["rescale_host"] = rescale_host
            cache["rescale_dev"] = jnp.float32(rescale_host)
        rescale = cache["rescale_dev"]
        pv = tuple(p._ndarray._data for p in params)
        gv = tuple(g._data for g in grads)
        sv = tuple(_fs.state_data(s) for s in states)
        shard_cfg = cache.get("shard_cfg")
        if shard_cfg is not None:
            # jit with in_shardings rejects committed buffers at another
            # layout — place (and launder donated) inputs; identity at
            # steady state
            pv, gv, sv = shard_cfg.place_args(pv, gv, sv, donate_params)
        try:
            new_p, new_s, vals2 = entry(pv, gv, sv, st["vals"], lrs, wds,
                                        rescale)
        except Exception:
            # roll the count mirror back; the eager path re-counts
            optim.num_update, optim._index_update_count = snap[0], snap[1]
            _fs._CACHE.note_fallback()
            self._fused_broken = True
            self._fused = None
            self._invalidate_fused_state()
            return False
        st["vals"] = vals2
        st["expected_num_update"] = optim.num_update
        st["dirty"] = True
        for p, w2 in zip(params, new_p):
            p.data()._data = w2
        for s, s2 in zip(states, new_s):
            _fs.rebind_state(s, s2)
        return True

    def _shard_token(self):
        """Cheap identity token for the active sharding declaration —
        part of the per-step cache token so entering/leaving a
        ``sharding.plan_scope`` (or toggling ZeRO-1) rebuilds the fused
        group instead of reusing the other layout's executable."""
        from .. import sharding as _shard

        ctx = _shard.current_plan()
        if ctx is None:
            return None
        return (id(ctx[0]), id(ctx[1]), _shard.zero1_enabled())

    def _fused_group(self, kernel_key, scaler_cfg, donate_params):
        """Work set + LRU cache key for a fused step over the current
        parameter group: a dict, the sentinel ``"empty"`` (nothing has
        grad_req != null — the step is a no-op), or None (sparse or
        tracer gradients force the eager path)."""
        from ..ndarray import sparse as _sp
        from ..ndarray import registry as _registry

        optim = self._optimizer
        work = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not work:
            return "empty"
        params = [self._params[i] for i in work]
        grads = [p.grad() for p in params]
        if any(isinstance(g, _sp.BaseSparseNDArray) for g in grads) \
                or _fs.has_tracer([g.data for g in grads]):
            return None
        mp_flags = tuple(
            bool(optim.multi_precision and optim._is_half(p.data()))
            for p in params)
        states = [self._states[i] for i in work]
        sig = tuple(
            (tuple(p.shape), str(p.data().data.dtype),
             str(g.data.dtype), _fs.state_sig(s))
            for p, g, s in zip(params, grads, states))
        from .. import sharding as _shard

        shard_cfg = _shard.fused_shard_cfg(
            [(p.name, tuple(p.shape)) for p in params],
            [_fs.state_sig(s) for s in states]) \
            if self._shard_token() is not None else None
        key = (type(optim).__name__, kernel_key, mp_flags, sig,
               scaler_cfg, self._distributed, donate_params,
               _registry.amp_version(),
               None if shard_cfg is None else shard_cfg.salt)
        return {"work": work, "params": params, "grads": grads,
                "states": states, "mp_flags": mp_flags, "key": key,
                "shard_cfg": shard_cfg}

    def _fused_entry(self, group, kernel, scaler_cfg, donate_params):
        """The cached fused-step executable for a ``_fused_group`` —
        ONE construction site shared by the step loop and warmup, so
        both always build identical entries for a key."""
        key = group["key"]
        entry = _fs._CACHE.lookup(key)
        if entry is None:
            entry = _fs.build_executable(kernel, group["mp_flags"],
                                         scaler_cfg, donate_params,
                                         cache_key=key,
                                         shard_cfg=group.get("shard_cfg"))
            _fs._CACHE.insert(key, entry)
        return entry

    # -- AOT warmup ---------------------------------------------------------

    def warmup(self, shapes=None, block=None):
        """Precompile the training-path executables up front, so no
        compile stall (or retrace storm) lands mid-epoch — with the
        persistent compile cache armed (``MXNET_COMPILE_CACHE``), warm
        processes pull the executables straight off disk instead of
        compiling at all.

        Without arguments: resolves the fused train-step executable for
        the current parameter group via ``lower()``/``compile()`` only —
        nothing executes, no state changes.

        With ``block`` and ``shapes`` (an iterable of input shapes, one
        per expected batch signature/bucket): additionally runs one full
        forward/backward/``step`` per shape on zero inputs to warm every
        executable on the training path (eager-dispatch entries,
        hybridized CachedOp traces, the fused step), then restores
        parameters, gradients, optimizer state, AMP loss-scale state and
        the PRNG stream bit-for-bit, so training after ``warmup`` is
        byte-identical to training without it. Two caveats: (1) when
        deferred-init params materialize during warmup AND the forward
        draws stochastic keys (dropout), the cold run would interleave
        init and mask draws in one stream — that interleave cannot be
        reproduced ahead of time, so initialize shapes (or run one
        inference forward) first for strict parity; (2) warming shifts
        which step is the first *compiled* execution of each recording
        entry, which on fusion-sensitive graphs can differ from the
        uncached first run by an ulp (same class of caveat as
        BENCH_NOTES_r07). Best effort by design: executables keyed off
        the real loss head still compile on first use. Returns the
        number of shapes warmed."""
        if (block is None) != (shapes is None):
            # a half-specified call would silently warm NOTHING the
            # caller asked for — the mid-epoch stall this API exists to
            # prevent would land anyway
            raise ValueError(
                "Trainer.warmup needs BOTH shapes and block for the "
                "full forward/backward/step warmup (got only "
                f"{'shapes' if shapes is not None else 'block'}); call "
                "warmup() with neither to AOT-resolve just the fused "
                "step")
        if block is None:
            from .parameter import DeferredInitializationError

            try:
                self._warmup_fused()
            except DeferredInitializationError:
                pass  # shapes unknown until first forward: nothing to AOT
            return 0
        from .. import autograd, ndarray as nd, random as _mxrandom

        shapes = [tuple(s) for s in shapes]
        params = list(block.collect_params().values())
        if shapes and any(p._ndarray is None for p in params):
            # deferred-init params materialize on the first forward,
            # drawing initializer keys from the global stream — run that
            # forward NOW (grad/train modes off: no dropout draws, no BN
            # stat updates) so the snapshot below lands post-init, the
            # same stream position the first real forward would leave
            with autograd.pause(train_mode=False):
                block(nd.zeros(shapes[0]))
            params = list(block.collect_params().values())
        for p in self._params:
            if p not in params:
                params.append(p)
        # device step-state is authoritative while fused stepping (loss
        # scale, skip-drifted update count): pull it into the host
        # mirrors FIRST, or the snapshots below would capture — and the
        # restore would resurrect — stale pre-sync values
        self._sync_fused_state()
        self._invalidate_fused_state()
        # param buffers are donated only under MXNET_FUSED_STEP_DONATE —
        # copy then; refs suffice otherwise (jax arrays are immutable).
        # Optimizer-state buffers are ALWAYS donated by the fused step,
        # so their snapshot must be device copies (state_copy).
        copy_params = _fs.donate_params_enabled()
        snap_params = [(p,
                        jnp.array(p._ndarray._data, copy=True)
                        if copy_params else p._ndarray._data,
                        None if p._ndarray._grad is None
                        else p._ndarray._grad._data) for p in params
                       if getattr(p, "_ndarray", None) is not None]
        optim = self._optimizer
        snap_optim = (optim.num_update, optim.begin_num_update,
                      dict(optim._index_update_count))
        if not self._states_created:
            self._create_states()
        snap_states = [_fs.state_copy(s) for s in self._states]
        scaler = getattr(self, "_amp_loss_scaler", None)
        snap_scaler = None if scaler is None else \
            (scaler._loss_scale, scaler._unskipped)
        snap_skips = self._fused_skips_host
        snap_key = _mxrandom._STATE.key
        count = 0
        try:
            for shape in shapes:
                x = nd.zeros(tuple(shape))
                with autograd.record():
                    y = block(x)
                    outs = y if isinstance(y, (list, tuple)) else [y]
                    loss = outs[0].sum()
                    for o in outs[1:]:
                        loss = loss + o.sum()
                loss.backward()
                self.step(batch_size=max(int(shape[0]), 1)
                          if shape else 1)
                count += 1
        finally:
            for p, data, grad in snap_params:
                p._ndarray._data = data
                if grad is not None and p._ndarray._grad is not None:
                    p._ndarray._grad._data = grad
            (optim.num_update, optim.begin_num_update, counts) = snap_optim
            optim._index_update_count = counts
            for s, data in zip(self._states, snap_states):
                _fs.rebind_state(s, data)
            if scaler is not None:
                scaler._loss_scale, scaler._unskipped = snap_scaler
            self._invalidate_fused_state()
            self._fused_skips_host = snap_skips
            _mxrandom._STATE.key = snap_key
        return count

    def _warmup_fused(self):
        """Resolve (disk-load or AOT-compile) the fused-step executable
        without executing it. No-op when the fused path cannot serve the
        current parameter group."""
        if not _fs.fused_step_enabled() or self._fused_broken:
            return False
        kern = self._optimizer._fused_kernel()
        if kern is None:
            return False
        if not self._states_created:
            self._create_states()
        kernel_key, kernel = kern
        scaler = getattr(self, "_amp_loss_scaler", None)
        scaler_cfg = None if scaler is None else \
            (float(scaler._scale_factor), int(scaler._scale_window))
        donate_params = _fs.donate_params_enabled()
        group = self._fused_group(kernel_key, scaler_cfg, donate_params)
        if group == "empty" or group is None:
            return False
        entry = self._fused_entry(group, kernel, scaler_cfg,
                                  donate_params)
        st = self._ensure_fused_state(scaler)
        pv = tuple(p._ndarray._data for p in group["params"])
        gv = tuple(g._data for g in group["grads"])
        sv = tuple(_fs.state_data(s) for s in group["states"])
        if group.get("shard_cfg") is not None:
            pv, gv, sv = group["shard_cfg"].place_args(
                pv, gv, sv, donate_params)
        n = len(group["work"])
        entry.prepare((pv, gv, sv, st["vals"],
                       jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32),
                       jnp.float32(1.0)))
        return True

    # -- stepping -----------------------------------------------------------

    def step(self, batch_size, ignore_stale_grad=False):
        """Rescale by 1/batch_size, allreduce, overflow-check, update —
        as ONE compiled donated executable on the fused path (reference:
        trainer.py step + amp/loss_scaler.py skip-step via
        multi_all_finite). With an AMP loss scaler attached
        (amp.init_trainer), gradients are additionally divided by the
        loss scale and the whole step is skipped on overflow; the
        scale's grow/backoff state lives on device (no host round-trip)
        and is synced back on ``scaler.loss_scale`` reads/save_states."""
        scaler = getattr(self, "_amp_loss_scaler", None)
        # allreduce BEFORE the overflow check — for the eager AND fused
        # paths alike: every worker then sees the same reduced gradients
        # and takes the same skip/apply branch (a local check would
        # desync workers and hang the next collective). It runs HERE,
        # once, so a fused executable that fails mid-flight cannot lead
        # to a second reduction on the eager fallback. Multi-process
        # host_local<->global array conversion can't live inside jit, so
        # the collective runs as its own compiled program between
        # backward and the fused update; single process it is a no-op.
        self.allreduce_grads()
        if _fs.fused_step_enabled() and not self._fused_broken and \
                self._fused_step(batch_size, scaler):
            return
        if self._fused_state is not None:
            # fused was active earlier (env toggle / bypass): device
            # state is authoritative — pull it back before eager math
            self._sync_fused_state()
            self._invalidate_fused_state()
        rescale = self._scale / batch_size
        if scaler is not None:
            if scaler.has_overflow(self._params):
                scaler.update_scale(True)
                return  # skip the update entirely
            # divide by the CURRENT scale (the one the loss was multiplied
            # by); grow the scale only after the step is applied
            rescale = rescale / scaler.loss_scale
        self._optimizer.rescale_grad = rescale
        self.update(batch_size, ignore_stale_grad=ignore_stale_grad,
                    _skip_rescale=True)
        self._optimizer.rescale_grad = self._scale
        if scaler is not None:
            scaler.update_scale(False)

    def update(self, batch_size, ignore_stale_grad=False,
               _skip_rescale=False):
        if not _skip_rescale:
            self._optimizer.rescale_grad = self._scale / batch_size
        if not self._states_created:
            self._create_states()
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            self._optimizer.update_multi_precision(i, p.data(), p.grad(),
                                                   self._states[i])

    def zero_grad(self):
        for p in self._params:
            p.zero_grad()

    def save_states(self, fname):
        """Reference: trainer.py save_states (optimizer state incl. kvstore
        resident state). The AMP loss-scaler state rides along, and any
        device-resident fused-step state is synced into the host mirrors
        first."""
        assert self._optimizer is not None
        if not self._states_created:
            self._create_states()
        # speculation from a backward that already ran must not
        # straddle the capture boundary (see _abandon_speculation)
        self._abandon_speculation()
        self._sync_fused_state()
        import pickle

        from .. import ndarray as nd

        def dump(v):
            if isinstance(v, nd.NDArray):
                # checkpointing is an intentional full sync, off the
                # step loop's hot path
                return ("nd", v.asnumpy())  # graft-lint: allow(L401)
            if isinstance(v, tuple):
                return ("tuple", tuple(dump(s) for s in v))
            return ("raw", v)

        payload = {"num_update": self._optimizer.num_update,
                   "states": [dump(s) for s in self._states]}
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            payload["loss_scaler"] = {"loss_scale": scaler._loss_scale,
                                      "unskipped": scaler._unskipped}
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        import pickle

        # restoring over a round whose backward already dispatched
        # speculative reductions: drop them, or the next step() flush
        # would bind pre-restore gradient math into the restored state
        self._abandon_speculation()
        with open(fname, "rb") as f:
            payload = pickle.load(f)

        # shared walk (fused_step.state_tree_restore): rebuilds the
        # tagged tree AND launders every buffer through state_adopt —
        # the fused step donates state buffers, and donating raw
        # device_put uploads corrupts memory on the jaxlib-0.4.37 CPU
        # client
        self._states = [_fs.state_tree_restore(s)
                        for s in payload["states"]]
        self._states_created = True
        self._optimizer.num_update = payload["num_update"]
        self._optimizer.begin_num_update = payload["num_update"]
        scaler_state = payload.get("loss_scaler")
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler_state is not None and scaler is not None:
            scaler._loss_scale = float(scaler_state["loss_scale"])
            scaler._unskipped = int(scaler_state["unskipped"])
        # device step-state is stale now; re-seed from the restored host
        # values on the next fused step
        self._invalidate_fused_state()
