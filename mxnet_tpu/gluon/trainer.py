"""Gluon Trainer.

TPU-native equivalent of python/mxnet/gluon/trainer.py (reference:
Trainer:27, kvstore wiring :169-217, step/allreduce_grads/update). The
reference pushes grads through kvstore (CPU/GPU reduce or ps-lite); here
single-host aggregation is implicit (one logical grad per param) and
multi-host runs ride `mxnet_tpu.parallel` collectives. The actual update
is executed as ONE fused jitted function over all parameters per optimizer
step — the analog of the reference's multi-tensor fused update ops
(src/operator/contrib/preloaded_multi_sgd.cc) — falling back to per-param
eager updates for optimizers without a fused path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contexts = None
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore if isinstance(kvstore, str) else \
            getattr(kvstore, "type", "device")
        self._kvstore = kvstore if isinstance(kvstore, kvs.KVStore) else None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._distributed = self._kvstore_type.startswith("dist")
        self._states_created = False
        self._fused = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _create_states(self):
        self._states = [
            self._optimizer.create_state_multi_precision(i, p.data())
            for i, p in enumerate(self._params)]
        self._states_created = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def allreduce_grads(self):
        """Cross-worker gradient all-reduce (reference: trainer.py
        _allreduce_grads via kvstore push/pull). Single host: no-op (one
        logical grad); dist: ICI/DCN psum via parallel.all_reduce."""
        if self._distributed:
            from .. import parallel

            for p in self._params:
                if p.grad_req != "null":
                    g = p.grad()
                    g._data = parallel.all_reduce(g).data

    def step(self, batch_size, ignore_stale_grad=False):
        """Rescale by 1/batch_size, allreduce, update
        (reference: trainer.py step). With an AMP loss scaler attached
        (amp.init_trainer), gradients are additionally divided by the loss
        scale and the whole step is skipped on overflow (reference:
        amp/loss_scaler.py skip-step via multi_all_finite)."""
        rescale = self._scale / batch_size
        scaler = getattr(self, "_amp_loss_scaler", None)
        # allreduce BEFORE the overflow check: every worker then sees the
        # same reduced gradients and takes the same skip/apply branch (a
        # local check would desync workers and hang the next collective)
        self.allreduce_grads()
        if scaler is not None:
            if scaler.has_overflow(self._params):
                scaler.update_scale(True)
                return  # skip the update entirely
            # divide by the CURRENT scale (the one the loss was multiplied
            # by); grow the scale only after the step is applied
            rescale = rescale / scaler.loss_scale
        self._optimizer.rescale_grad = rescale
        self.update(batch_size, ignore_stale_grad=ignore_stale_grad,
                    _skip_rescale=True)
        self._optimizer.rescale_grad = self._scale
        if scaler is not None:
            scaler.update_scale(False)

    def update(self, batch_size, ignore_stale_grad=False,
               _skip_rescale=False):
        if not _skip_rescale:
            self._optimizer.rescale_grad = self._scale / batch_size
        if not self._states_created:
            self._create_states()
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            self._optimizer.update_multi_precision(i, p.data(), p.grad(),
                                                   self._states[i])

    def zero_grad(self):
        for p in self._params:
            p.zero_grad()

    def save_states(self, fname):
        """Reference: trainer.py save_states (optimizer state incl. kvstore
        resident state)."""
        assert self._optimizer is not None
        if not self._states_created:
            self._create_states()
        import pickle

        from .. import ndarray as nd

        def dump(v):
            if isinstance(v, nd.NDArray):
                return ("nd", v.asnumpy())
            if isinstance(v, tuple):
                return ("tuple", tuple(dump(s) for s in v))
            return ("raw", v)

        payload = {"num_update": self._optimizer.num_update,
                   "states": [dump(s) for s in self._states]}
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        import pickle

        from .. import ndarray as nd

        with open(fname, "rb") as f:
            payload = pickle.load(f)

        def restore(v):
            tag, val = v
            if tag == "nd":
                return nd.array(val)
            if tag == "tuple":
                return tuple(restore(s) for s in val)
            return val

        self._states = [restore(s) for s in payload["states"]]
        self._states_created = True
        self._optimizer.num_update = payload["num_update"]
        self._optimizer.begin_num_update = payload["num_update"]
