"""Gluon contrib (reference: python/mxnet/gluon/contrib/__init__.py)."""
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import cnn  # noqa: F401
from . import data  # noqa: F401
from . import estimator  # noqa: F401
