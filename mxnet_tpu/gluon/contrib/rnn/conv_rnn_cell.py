"""Convolutional recurrent cells (reference:
python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py — _BaseConvRNNCell and
the Conv{1,2,3}D{RNN,LSTM,GRU}Cell family).

State carries spatial structure: gates are computed by an input conv
plus a state conv instead of two matmuls — on TPU both lower to XLA
conv_general_dilated on the MXU, so a conv-LSTM step is exactly as
MXU-friendly as a dense LSTM step of the same FLOPs.
"""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * n


class _ConvRNNCellBase(HybridRecurrentCell):
    """Shared machinery: i2h/h2h convolutions producing `ngates *
    hidden_channels` feature maps. `input_shape` = (C, *spatial) is
    required up front (reference conv cells require it too — the state
    shape must be known before the first step)."""

    _ngates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(int(s) for s in input_shape)
        dims = len(self._input_shape) - 1
        if dims not in (1, 2, 3):
            raise ValueError(
                f"input_shape must be (C, *spatial) with 1-3 spatial "
                f"dims, got {input_shape}")
        self._dims = dims
        self._hidden_channels = int(hidden_channels)
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    "h2h_kernel dims must be odd (same-size state)")
        self._i2h_pad = tuple(k // 2 for k in self._i2h_kernel)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        in_c = self._input_shape[0]
        out_c = self._ngates * self._hidden_channels
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(out_c, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(out_c, self._hidden_channels) + self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(out_c,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(out_c,), init=h2h_bias_initializer)

    _num_states = 1

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + \
            self._input_shape[1:]
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}
                for _ in range(self._num_states)]

    def _gates(self, F, inputs, prev_h, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        out_c = self._ngates * self._hidden_channels
        i2h = F.convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=out_c)
        h2h = F.convolution(prev_h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=out_c)
        return i2h, h2h

    def _act(self, F, x):
        if self._activation in ("relu", "tanh", "sigmoid", "softrelu"):
            return F.activation(x, act_type=self._activation)
        return getattr(F, self._activation)(x)


class _ConvRNNCell(_ConvRNNCellBase):
    """h' = act(conv(x) + conv(h)) (reference _ConvRNNCell)."""

    _ngates = 1
    _num_states = 1

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states[0], i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_ConvRNNCellBase):
    """Shi et al. ConvLSTM (reference _ConvLSTMCell; gate order i,f,g,o
    matching the dense LSTMCell/cuDNN layout)."""

    _ngates = 4
    _num_states = 2

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states[0], i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        ig, fg, gg, og = F.split(gates, num_outputs=4, axis=1)
        ig = F.sigmoid(ig)
        fg = F.sigmoid(fg)
        gg = self._act(F, gg)
        og = F.sigmoid(og)
        next_c = fg * states[1] + ig * gg
        next_h = og * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_ConvRNNCellBase):
    """Conv GRU (reference _ConvGRUCell; gate order r,z,n)."""

    _ngates = 3
    _num_states = 1

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, inputs, states[0], i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        cand = self._act(F, i2h_n + reset * h2h_n)
        next_h = (1.0 - update) * cand + update * states[0]
        return next_h, [next_h]


def _specialize(base, dims, name, doc_ref):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, **kwargs):
        if len(tuple(input_shape)) != dims + 1:
            raise ValueError(
                f"{name} expects input_shape=(C, {dims} spatial dims), "
                f"got {input_shape}")
        base.__init__(self, input_shape, hidden_channels,
                      i2h_kernel=i2h_kernel, h2h_kernel=h2h_kernel,
                      **kwargs)

    cls = type(name, (base,), {
        "__init__": __init__,
        "__doc__": f"Reference: conv_rnn_cell.py {doc_ref}."})
    return cls


Conv1DRNNCell = _specialize(_ConvRNNCell, 1, "Conv1DRNNCell",
                            "Conv1DRNNCell")
Conv2DRNNCell = _specialize(_ConvRNNCell, 2, "Conv2DRNNCell",
                            "Conv2DRNNCell")
Conv3DRNNCell = _specialize(_ConvRNNCell, 3, "Conv3DRNNCell",
                            "Conv3DRNNCell")
Conv1DLSTMCell = _specialize(_ConvLSTMCell, 1, "Conv1DLSTMCell",
                             "Conv1DLSTMCell")
Conv2DLSTMCell = _specialize(_ConvLSTMCell, 2, "Conv2DLSTMCell",
                             "Conv2DLSTMCell")
Conv3DLSTMCell = _specialize(_ConvLSTMCell, 3, "Conv3DLSTMCell",
                             "Conv3DLSTMCell")
Conv1DGRUCell = _specialize(_ConvGRUCell, 1, "Conv1DGRUCell",
                            "Conv1DGRUCell")
Conv2DGRUCell = _specialize(_ConvGRUCell, 2, "Conv2DGRUCell",
                            "Conv2DGRUCell")
Conv3DGRUCell = _specialize(_ConvGRUCell, 3, "Conv3DGRUCell",
                            "Conv3DGRUCell")
