"""Contrib recurrent cells (reference:
python/mxnet/gluon/contrib/rnn/rnn_cell.py — VariationalDropoutCell,
LSTMPCell)."""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell, ModifierCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Gal & Ghahramani variational dropout: ONE dropout mask per unroll
    for each of inputs/states/outputs, reused at every time step
    (reference contrib rnn_cell.py VariationalDropoutCell — a fresh mask
    per step would be ordinary DropoutCell)."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def reset(self):
        super().reset()
        # masks are PER-UNROLL: a new sequence draws new masks
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, F, name, p, like):
        cached = getattr(self, name)
        if cached is None:
            # Dropout(ones) IS the (scaled) mask; sampled once, reused
            cached = F.dropout(F.ones_like(like), p=p)
            setattr(self, name, cached)
        return cached

    def hybrid_forward(self, F, inputs, states):
        if self.drop_inputs > 0.:
            inputs = inputs * self._mask(F, "_input_mask",
                                         self.drop_inputs, inputs)
        if self.drop_states > 0.:
            states = [states[0] * self._mask(F, "_state_mask",
                                             self.drop_states, states[0])
                      ] + list(states[1:])  # mask h only, never the cell
        output, next_states = self.base_cell(inputs, states)
        if self.drop_outputs > 0.:
            output = output * self._mask(F, "_output_mask",
                                         self.drop_outputs, output)
        return output, next_states

    def __repr__(self):
        return (f"VariationalDropoutCell(in={self.drop_inputs}, "
                f"state={self.drop_states}, out={self.drop_outputs}, "
                f"base={type(self.base_cell).__name__})")


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a projection layer on the hidden state (reference
    contrib rnn_cell.py LSTMPCell, after Sak et al. 2014): the recurrent
    state is the PROJECTED h (size projection_size), the cell state
    keeps hidden_size — cuts the h2h matmul from h*4h to p*4h."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = int(hidden_size)
        self._projection_size = int(projection_size)
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        prev_r, prev_c = states
        i2h = F.fully_connected(inputs, i2h_weight, i2h_bias,
                                num_hidden=4 * self._hidden_size)
        h2h = F.fully_connected(prev_r, h2h_weight, h2h_bias,
                                num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        ig, fg, gg, og = F.split(gates, num_outputs=4, axis=-1)
        next_c = F.sigmoid(fg) * prev_c + F.sigmoid(ig) * F.tanh(gg)
        next_h = F.sigmoid(og) * F.tanh(next_c)
        next_r = F.fully_connected(next_h, h2r_weight, no_bias=True,
                                   num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
