"""Gluon Fit API (reference:
python/mxnet/gluon/contrib/estimator/__init__.py)."""
from .estimator import Estimator
from .event_handler import *  # noqa: F401,F403
from .event_handler import __all__ as _eh_all

__all__ = ["Estimator"] + list(_eh_all)
