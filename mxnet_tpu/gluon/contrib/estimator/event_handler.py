"""Estimator event handlers (reference:
python/mxnet/gluon/contrib/estimator/event_handler.py)."""
from __future__ import annotations

import logging
import os
import time

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch/max_batch (reference: event_handler.py:78)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Updates training metrics each batch (reference:
    event_handler.py:126)."""

    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.train_metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        for metric in self.train_metrics:
            if "loss" in metric.name.lower():
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Runs validation every `epoch_period` epochs (reference:
    event_handler.py:182)."""

    def __init__(self, val_data, eval_fn, val_metrics=None, epoch_period=1,
                 batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.val_metrics = val_metrics or []
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data,
                         val_metrics=self.val_metrics)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data,
                         val_metrics=self.val_metrics)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Logs metrics per epoch/interval (reference: event_handler.py:248)."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.time() - self.train_start
        self.logger.info("Train finished in %.2fs: %s", t,
                         self._fmt_metrics())

    def _fmt_metrics(self):
        return ", ".join("%s=%.6f" % m.get() for m in self.metrics)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.time() - self.epoch_start
        self.logger.info("Epoch %d finished in %.2fs: %s",
                         self.current_epoch, t, self._fmt_metrics())
        self.current_epoch += 1
        self.batch_index = 0

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int) and \
                (self.batch_index + 1) % self.log_interval == 0:
            self.logger.info("Epoch %d batch %d: %s", self.current_epoch,
                             self.batch_index + 1, self._fmt_metrics())
        self.batch_index += 1


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Saves model/trainer state periodically, tracking a monitored metric
    (reference: event_handler.py:358)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="auto", save_best=False, epoch_period=1,
                 max_checkpoints=5):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.max_checkpoints = max_checkpoints
        self.current_epoch = 0
        self.saved = []
        if mode == "auto" and monitor is not None:
            mode = "max" if "acc" in monitor.name.lower() else "min"
        self.mode = mode
        self.best = None
        os.makedirs(model_dir, exist_ok=True)

    def _better(self, value):
        if self.best is None:
            return True
        return value > self.best if self.mode == "max" else \
            value < self.best

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.current_epoch % self.epoch_period:
            return
        path = os.path.join(
            self.model_dir,
            f"{self.model_prefix}-epoch{self.current_epoch}.params")
        estimator.net.save_parameters(path)
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            if os.path.isfile(old):
                os.remove(old)
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            if self._better(value):
                self.best = value
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stops when the monitored metric stops improving (reference:
    event_handler.py:570)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        if mode == "auto":
            mode = "max" if "acc" in monitor.name.lower() else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.best = None
        self.wait = 0
        self.stop_training = False

    def epoch_end(self, estimator, *args, **kwargs):
        _, value = self.monitor.get()
        improved = self.best is None or (
            value - self.best > self.min_delta if self.mode == "max"
            else self.best - value > self.min_delta)
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
