"""Gluon Fit API (reference:
python/mxnet/gluon/contrib/estimator/estimator.py:40,236 — the 1.5
release's Estimator.fit)."""
from __future__ import annotations

from .... import autograd
from ....metric import Loss as LossMetric, Accuracy, EvalMetric
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, LoggingHandler,
                            ValidationHandler)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, metrics=None, trainer=None, context=None):
        self.net = net
        self.loss = loss
        mets = metrics if metrics is not None else [Accuracy()]
        self.train_metrics = ([mets] if isinstance(mets, EvalMetric)
                              else list(mets))
        self.train_metrics.append(LossMetric(name="loss"))
        self.trainer = trainer
        if self.trainer is None:
            from ...trainer import Trainer

            self.trainer = Trainer(net.collect_params(), "adam",
                                   {"learning_rate": 1e-3})

    def evaluate(self, val_data, val_metrics):
        for metric in val_metrics:
            metric.reset()
        if hasattr(val_data, "reset"):
            val_data.reset()
        for batch in val_data:
            data, label = self._unpack(batch)
            pred = self.net(data)
            loss = self.loss(pred, label)
            for metric in val_metrics:
                if "loss" in metric.name.lower():
                    metric.update(0, loss)
                else:
                    metric.update(label, pred)

    @staticmethod
    def _unpack(batch):
        from ....ndarray import NDArray

        if isinstance(batch, (list, tuple)):  # DataLoader-style pair
            return batch[0], batch[1]
        if isinstance(batch, NDArray):
            raise ValueError("batch must be (data, label) or a DataBatch")
        return batch.data[0], batch.label[0]  # DataBatch

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None):
        """Reference: estimator.py:236 fit."""
        if epochs is None and batches is None:
            epochs = 1
        handlers = list(event_handlers or [])
        stopper = StoppingHandler(max_epoch=epochs, max_batch=batches)
        handlers.append(stopper)
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            self.val_metrics = [type(m)() for m in self.train_metrics[:-1]]
            self.val_metrics.append(LossMetric(name="val_loss"))
            handlers.append(ValidationHandler(val_data, self.evaluate,
                                              self.val_metrics))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))

        def fire(kind, *args, **kwargs):
            for h in handlers:
                m = getattr(h, kind, None)
                if m is not None:
                    m(self, *args, **kwargs)

        fire("train_begin")
        while not self._stopped(handlers):
            fire("epoch_begin")
            if hasattr(train_data, "reset"):
                train_data.reset()
            for batch in train_data:
                data, label = self._unpack(batch)
                fire("batch_begin")
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                bs = data.shape[0]
                self.trainer.step(bs)
                fire("batch_end", pred=pred, label=label, loss=loss)
                if self._stopped(handlers):
                    break
            fire("epoch_end")
        fire("train_end")

    @staticmethod
    def _stopped(handlers):
        return any(getattr(h, "stop_training", False) for h in handlers)
