"""Language-model datasets (reference: gluon/contrib/data/text.py
WikiText2/WikiText103).

The reference downloads the corpus zips from the MXNet S3 bucket; TPU
training hosts are commonly egress-free, so these classes read an
already-present token file under `root` (same file names the reference
unpacks: wiki.{train,valid,test}.tokens) and raise a clear error naming
the expected path when it is absent. Parsing semantics match the
reference: whitespace tokens per non-empty line, <eos> appended, stream
flattened, (data, label) = (w[:-1], w[1:]) reshaped to seq_len rows.
"""
from __future__ import annotations

import os

import numpy as onp

from ...data import dataset as _dataset
from ....contrib.text.vocab import Vocabulary
from ....contrib.text.utils import count_tokens_from_str

EOS_TOKEN = "<eos>"

__all__ = ["WikiText2", "WikiText103"]


class _WikiText(_dataset.Dataset):
    _files = {"train": "wiki.train.tokens", "validation": "wiki.valid.tokens",
              "test": "wiki.test.tokens"}

    def __init__(self, root, segment="train", vocab=None, seq_len=35):
        if segment not in self._files:
            raise ValueError(f"segment must be one of {list(self._files)}")
        self._root = os.path.expanduser(root)
        self._segment = segment
        self._seq_len = seq_len
        self.vocabulary = vocab
        self._get_data()

    @property
    def frequencies(self):
        return self._frequencies

    def _get_data(self):
        path = os.path.join(self._root, self._files[self._segment])
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} not found. This environment has no dataset "
                f"egress: place the extracted WikiText token file there "
                f"(the reference unpacks the same name from "
                f"{type(self).__name__.lower()}-v1.zip)")
        with open(path, encoding="utf8") as fin:
            content = fin.read()
        self._frequencies = count_tokens_from_str(content)
        if self.vocabulary is None:
            self.vocabulary = Vocabulary(
                self._frequencies, reserved_tokens=[EOS_TOKEN])
        lines = [ln.strip().split() for ln in content.splitlines()]
        stream = []
        for line in lines:
            if line:
                stream.extend(line)
                stream.append(EOS_TOKEN)
        idx = self.vocabulary.to_indices(stream)
        data = onp.asarray(idx[:-1], dtype=onp.int32)
        label = onp.asarray(idx[1:], dtype=onp.int32)
        n = (len(data) // self._seq_len) * self._seq_len
        from .... import nd

        self._data = nd.array(
            data[:n].reshape(-1, self._seq_len), dtype="int32")
        self._label = nd.array(
            label[:n].reshape(-1, self._seq_len), dtype="int32")

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


class WikiText2(_WikiText):
    """WikiText-2 (reference: contrib/data/text.py WikiText2)."""


class WikiText103(_WikiText):
    """WikiText-103 (reference: contrib/data/text.py WikiText103)."""
