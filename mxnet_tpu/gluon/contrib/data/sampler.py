"""Contrib samplers (reference: gluon/contrib/data/sampler.py)."""
from __future__ import annotations

from ...data import sampler as _sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(_sampler.Sampler):
    """Sample [0, length) at fixed `interval` strides; with rollover the
    skipped phases follow (0, k, 2k, ..., 1, k+1, ...)."""

    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise ValueError(
                f"interval {interval} must be <= length {length}")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for start in range(self._interval if self._rollover else 1):
            yield from range(start, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return (self._length + self._interval - 1) // self._interval
