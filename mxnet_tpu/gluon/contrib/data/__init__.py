"""Contrib datasets/samplers (reference: python/mxnet/gluon/contrib/data)."""
from .sampler import IntervalSampler  # noqa: F401
from . import text  # noqa: F401
