"""Contrib neural network layers (reference:
python/mxnet/gluon/contrib/nn/__init__.py)."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, PixelShuffle1D, PixelShuffle2D,
                           SyncBatchNorm)
from .moe import SwitchMoE

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "PixelShuffle1D", "PixelShuffle2D", "SyncBatchNorm", "SwitchMoE"]
