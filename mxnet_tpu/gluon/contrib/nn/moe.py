"""Gluon layer over the expert-parallel Switch-MoE FFN
(parallel/moe.py). NEW capability vs the reference zoo — the Gluon
face of SURVEY §5.7's scale features, alongside SyncBatchNorm.
"""
from __future__ import annotations

from ...block import HybridBlock

__all__ = ["SwitchMoE"]


class SwitchMoE(HybridBlock):
    """Mixture-of-experts FFN block: top-1 (Switch) routing, experts
    sharded over the mesh's ``axis_name`` axis when a mesh is active
    (``parallel.mesh_scope`` or an explicit ``mesh=``), single-device
    math otherwise.

    forward(x) -> (out, aux_loss): add ``aux_weight * aux_loss`` to the
    training objective for load balancing; out excludes the residual
    (callers add ``x + out`` — dropped-over-capacity tokens then pass
    through untouched).

    Eager calls on a mesh bridge single-device buffers to the mesh and
    back each step (re-tracing the vjp) — fine for interactive use;
    production training should run the layer inside one compiled step
    (SPMDTrainer / jax.jit), where inputs are tracers and the bridge is
    bypassed entirely.
    """

    def __init__(self, num_experts, hidden_size, in_units=0,
                 capacity_factor=1.25, axis_name="ep", mesh=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._E = int(num_experts)
        self._H = int(hidden_size)
        self._cf = float(capacity_factor)
        self._axis = axis_name
        self._mesh = mesh
        D = int(in_units)
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(D, self._E),
                allow_deferred_init=True)
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(self._E, D, self._H),
                allow_deferred_init=True)
            self.expert_b1 = self.params.get(
                "expert_b1", shape=(self._E, self._H), init="zeros")
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(self._E, self._H, D),
                allow_deferred_init=True)
            self.expert_b2 = self.params.get(
                "expert_b2", shape=(self._E, D), init="zeros",
                allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        D = x.shape[-1]
        self.gate_weight.shape = (D, self._E)
        self.expert_w1.shape = (self._E, D, self._H)
        self.expert_w2.shape = (self._E, self._H, D)
        self.expert_b2.shape = (self._E, D)

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        import jax

        from ....ndarray.registry import apply_pure
        from ....parallel.mesh import current_mesh
        from ....parallel.moe import moe_ffn, moe_specs

        mesh = self._mesh or current_mesh()
        axis, cf = self._axis, self._cf
        args = [x, gate_weight, expert_w1, expert_b1, expert_w2,
                expert_b2]
        from ....ndarray.ndarray import _is_tracer

        caller_dev = None
        if mesh is not None and axis in mesh.axis_names \
                and mesh.shape[axis] > 1 \
                and getattr(x, "_data", None) is not None \
                and not _is_tracer(x._data):
            devs = getattr(x._data.sharding, "device_set", None)
            if devs and len(devs) == 1:
                caller_dev = next(iter(devs))

        def pure(xv, gw, w1, b1, w2, b2):
            return moe_ffn(xv, gw, w1, b1, w2, b2, mesh=mesh,
                           axis_name=axis, capacity_factor=cf)

        if caller_dev is None:
            out, aux = apply_pure(pure, args)
            return out, aux
        # eager on a mesh: record the tape node ourselves with placement
        # shims — cotangents arrive committed to the caller's device and
        # must ride the mesh through the vjp; gradients come back to the
        # caller's device for the (single-device) optimizer update
        from ....ndarray import NDArray
        from .... import autograd
        from jax.sharding import NamedSharding

        _axes, bspec, espec, rep = moe_specs(mesh, axis)
        specs = [bspec, rep, espec, espec, espec, espec]
        # mesh-committed COPIES feed the computation; the caller's
        # buffers stay on their device (mutating them would poison
        # downstream eager math with mixed commitments)
        datas = [jax.device_put(a.data, NamedSharding(mesh, s))  # graft-lint: allow(L701)
                 for a, s in zip(args, specs)]
        if not autograd.is_recording():
            out_d, aux_d = pure(*datas)  # no vjp residuals at inference
            return (NDArray(jax.device_put(out_d, caller_dev)),
                    NDArray(jax.device_put(aux_d, caller_dev)))
        (out_d, aux_d), vjp_fn = jax.vjp(pure, *datas)

        def placed_vjp(cots, _vjp=vjp_fn):
            co, ca = cots
            co = jax.device_put(co, NamedSharding(mesh, bspec))  # graft-lint: allow(L701)
            ca = jax.device_put(ca, NamedSharding(mesh, rep))  # graft-lint: allow(L701)
            grads = _vjp((co, ca))
            return [jax.device_put(g, caller_dev) for g in grads]

        out = NDArray(jax.device_put(out_d, caller_dev))
        aux = NDArray(jax.device_put(aux_d, caller_dev))
        autograd._record_op(placed_vjp, list(args), [out, aux])
        return out, aux

    def __repr__(self):
        return (f"SwitchMoE(experts={self._E}, hidden={self._H}, "
                f"axis='{self._axis}')")
