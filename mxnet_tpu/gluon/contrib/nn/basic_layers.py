"""Contrib layers: Concurrent, HybridConcurrent, Identity, SparseEmbedding,
PixelShuffle, SyncBatchNorm.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py. SyncBatchNorm
(reference :165, backed by contrib/sync_batch_norm.cc cross-device
reduction) here computes batch stats with jax.lax.pmean over the data-
parallel mesh axis when running inside shard_map/pjit — the TPU-native
equivalent of the reference's NCCL-reduced statistics — and degrades to
plain BatchNorm outside a mapped context.
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential, BatchNorm


class Concurrent(Sequential):
    """Runs children on the same input, concatenating outputs along `axis`.

    Reference: contrib/nn/basic_layers.py:Concurrent."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import nd
        return nd.concat(*[block(x) for block in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent. Reference: contrib/nn/basic_layers.py:93."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        from .... import nd, symbol as _sym

        F = _sym if isinstance(x, _sym.Symbol) else nd
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """Reference: contrib/nn/basic_layers.py:Identity."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding backed by row_sparse gradient storage.

    Reference: contrib/nn/basic_layers.py:SparseEmbedding (grad_stype
    'row_sparse' so only touched rows are updated by sparse optimizers)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, grad_stype="row_sparse")

    def hybrid_forward(self, F, x, weight):
        return F.embedding(x, weight, **self._kwargs)


class PixelShuffle1D(HybridBlock):
    """Reference: contrib/nn/basic_layers.py:PixelShuffle1D."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        n, c, w = x.shape
        x = x.reshape(n, c // f, f, w)
        x = x.transpose((0, 1, 3, 2))
        return x.reshape(n, c // f, w * f)


class PixelShuffle2D(HybridBlock):
    """Reference: contrib/nn/basic_layers.py:PixelShuffle2D."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        f = factor if isinstance(factor, (list, tuple)) else (factor, factor)
        self._factors = tuple(int(v) for v in f)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        n, c, h, w = x.shape
        x = x.reshape(n, c // (f1 * f2), f1, f2, h, w)
        x = x.transpose((0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (f1 * f2), h * f1, w * f2)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib/nn/basic_layers.py:165,
    kernel src/operator/contrib/sync_batch_norm.cc).

    On TPU the cross-replica mean/var reduction is `lax.pmean` over the
    mesh's data-parallel axis — XLA lowers it to an ICI all-reduce fused
    into the step program, replacing the reference's explicit NCCL calls.
    `num_devices` is accepted for API parity but the axis size comes from
    the mesh. Outside a pmapped/shard_mapped context it behaves exactly
    like BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, axis_name="dp", axis=1, **kwargs):
        # axis=-1 supports NHWC nets (TPU-preferred layout), matching
        # the plain BatchNorm's axis parameter
        super().__init__(axis=axis, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices
        self._axis_name = axis_name

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from .... import autograd
        from jax import lax
        import jax.numpy as jnp

        import jax

        training = autograd.is_training() and not self._use_global_stats
        if not training or not isinstance(x.data, jax.core.Tracer):
            # eager single-device: identical to BatchNorm (and the eager
            # tape only records registered ops, so stay on that path)
            return super().hybrid_forward(F, x, gamma, beta, running_mean,
                                          running_var)
        ax = self._axis % len(x.shape)  # normalize -1 (NHWC) to positive
        red = tuple(i for i in range(len(x.shape)) if i != ax)
        xd = x.data
        mean = jnp.mean(xd, axis=red)
        sq = jnp.mean(xd * xd, axis=red)
        try:
            mean = lax.pmean(mean, self._axis_name)
            sq = lax.pmean(sq, self._axis_name)
        except NameError:
            # axis not bound: tracing outside shard_map/pmap (plain jit on
            # one device) — local stats are the correct stats there. A
            # *wrongly named* axis inside a mapped context also raises
            # NameError; pass axis_name= to match the mesh.
            pass
        var = sq - mean * mean
        shape = [1] * len(x.shape)
        shape[self._axis] = -1
        g = gamma.data.reshape(shape) if self._scale else 1.0
        b = beta.data.reshape(shape) if self._center else 0.0
        y = (xd - mean.reshape(shape)) * lax.rsqrt(
            var.reshape(shape) + self._epsilon) * g + b
        m = self._momentum
        running_mean._data = m * running_mean.data + (1 - m) * mean
        running_var._data = m * running_var.data + (1 - m) * var
        return type(x)(y)
