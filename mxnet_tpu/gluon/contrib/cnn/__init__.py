"""Contrib CNN layers (reference: python/mxnet/gluon/contrib/cnn)."""
from .conv_layers import DeformableConvolution  # noqa: F401
