"""Deformable convolution Gluon layer (reference:
python/mxnet/gluon/contrib/cnn/conv_layers.py DeformableConvolution).

One layer owning BOTH convolutions of Deformable ConvNets v1: a regular
conv producing the per-tap (dy, dx) offsets (zero-initialized so
training starts at the regular grid) and the deformable conv consuming
them (ops_contrib2.deformable_convolution — bilinear gathers on the
MXU-fed im2col).
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn.basic_layers import Activation

__all__ = ["DeformableConvolution"]


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class DeformableConvolution(HybridBlock):
    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout == "NCHW", \
            "deformable_convolution runs NCHW (reference kernel layout)"
        kernel_size = _pair(kernel_size)
        self._channels = channels
        self._kwargs_offset = {
            "kernel": kernel_size, "stride": _pair(strides),
            "dilate": _pair(dilation), "pad": _pair(padding),
            "num_filter": 2 * kernel_size[0] * kernel_size[1]
            * num_deformable_group,
            "num_group": groups, "no_bias": not offset_use_bias,
            "layout": layout}
        self._kwargs_conv = {
            "kernel": kernel_size, "stride": _pair(strides),
            "dilate": _pair(dilation), "pad": _pair(padding),
            "num_filter": channels, "num_group": groups,
            "num_deformable_group": num_deformable_group,
            "no_bias": not use_bias, "layout": layout}
        ic = in_channels // groups if in_channels else 0
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, ic) + kernel_size,
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer) \
                if use_bias else None
            self.offset_weight = self.params.get(
                "offset_weight",
                shape=(self._kwargs_offset["num_filter"], ic) + kernel_size,
                init=offset_weight_initializer, allow_deferred_init=True)
            self.offset_bias = self.params.get(
                "offset_bias", shape=(self._kwargs_offset["num_filter"],),
                init=offset_bias_initializer) if offset_use_bias else None
            self.act = Activation(activation) if activation else None

    def infer_param_shapes(self, x, *args):
        groups = self._kwargs_conv["num_group"]
        ic = x.shape[1] // groups
        k = self._kwargs_conv["kernel"]
        self.weight.shape = (self._channels, ic) + k
        self.offset_weight.shape = (
            self._kwargs_offset["num_filter"], ic) + k

    def hybrid_forward(self, F, x, weight, offset_weight, bias=None,
                       offset_bias=None):
        offset = F.convolution(x, offset_weight, offset_bias,
                               no_bias=offset_bias is None,
                               **{k: v for k, v in
                                  self._kwargs_offset.items()
                                  if k != "no_bias"})
        out = F.contrib.deformable_convolution(
            x, offset, weight, bias,
            **{k: v for k, v in self._kwargs_conv.items()
               if k != "no_bias"}, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out
