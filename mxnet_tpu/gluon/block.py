"""Gluon Block / HybridBlock / CachedOp.

TPU-native redesign of python/mxnet/gluon/block.py (reference: Block:228
child registry + collect_params:372; HybridBlock:838 deferred symbolic
trace, _build_cache:932 → CachedOp:969, hybridize:1039, export:1077) and
src/imperative/cached_op.{h,cc}.

Design: because every registered op body is traceable JAX, hybridization
does NOT need a separate symbolic language — ``hybridize()`` wraps the
block's imperative ``forward`` into a pure function over (param values,
PRNG key, inputs) and compiles it with ``jax.jit``. Parameter mutation
during forward (BatchNorm running stats) is detected at trace time and
returned as extra outputs, then written back — giving MXNet's stateful
semantics on a functional runtime. Under ``autograd.record`` the CachedOp
contributes ONE tape node whose vjp is the XLA-compiled transpose, exactly
like the reference records one node for the whole cached graph
(cached_op.cc Forward with recording).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import autograd
from .. import random as mxrandom
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]


class _BlockScope(threading.local):
    def __init__(self):
        self.current = None
        self.counters = {}


_SCOPE = _BlockScope()


def _gen_prefix(hint):
    if _SCOPE.current is None:
        counters = _SCOPE.counters
        base = ""
    else:
        counters = _SCOPE.current._counters
        base = _SCOPE.current.prefix
    idx = counters.get(hint, 0)
    counters[hint] = idx + 1
    return f"{base}{hint}{idx}_"


class _NameScope:
    def __init__(self, block):
        self._block = block
        self._old = None

    def __enter__(self):
        self._old = _SCOPE.current
        _SCOPE.current = self._block
        return self

    def __exit__(self, *exc):
        _SCOPE.current = self._old


class HookHandle:
    """Detachable registration (reference: gluon/utils.py HookHandle)."""

    def __init__(self, hooks, hook):
        self._hooks = hooks
        self._hook = hook

    def detach(self):
        try:
            self._hooks.remove(self._hook)
        except ValueError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.detach()


class Block:
    """Base building block (reference: gluon/block.py:228)."""

    def __init__(self, prefix=None, params=None):
        hint = type(self).__name__.lower()
        self._prefix = prefix if prefix is not None else _gen_prefix(hint)
        # Parameter NAMES may live under a different prefix than the
        # block (reference _BlockScope.create): with shared `params`,
        # this block's params are created under the SHARED dict's prefix
        # so lookups hit the shared entries; children of a sharing
        # parent inherit the parent's param-prefix remapping + _shared.
        parent = _SCOPE.current
        if params is not None:
            self._params = ParameterDict(params.prefix, shared=params)
        elif parent is not None and \
                parent.params.prefix != parent.prefix and \
                self._prefix.startswith(parent.prefix):
            local = self._prefix[len(parent.prefix):]
            self._params = ParameterDict(parent.params.prefix + local,
                                         shared=parent.params._shared)
        elif parent is not None and parent.params._shared is not None \
                and self._prefix.startswith(parent.prefix):
            self._params = ParameterDict(self._prefix,
                                         shared=parent.params._shared)
        else:
            self._params = ParameterDict(self._prefix)
        self._children = OrderedDict()
        self._reg_params = {}
        self._counters = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({key}): {block!r}"
                           for key, block in self._children.items())
        return s.format(name=type(self).__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, "_children", None)
            if existing is not None:
                self._children[name] = value
        elif isinstance(value, Parameter):
            if hasattr(self, "_reg_params"):
                self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    def name_scope(self):
        """Reference: gluon/block.py name_scope."""
        return _NameScope(self)

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """Reference: gluon/block.py:372 collect_params with regex select."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return HookHandle(self._forward_hooks, hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return HookHandle(self._forward_pre_hooks, hook)

    def register_op_hook(self, callback, monitor_all=False):
        """Tap every descendant block's outputs during forward
        (reference: block.py register_op_hook over CachedOp monitor
        callbacks). ``callback(name, array)``; with ``monitor_all``
        inputs are reported too. While any hook is attached, hybridized
        execution runs eagerly (the reference's monitor-mode slowdown)
        so taps fire with concrete arrays on EVERY call — on the whole
        subtree, including independently hybridized descendants. Returns
        a handle whose ``detach()`` removes this hook; the tap layer per
        block is shared, so handles detach safely in any order."""
        # a unique token per registration keys this hook's per-block
        # labels: a second hook registered deeper in the tree gets its
        # OWN prefix-relative labels, not the first registration's
        entry = (object(), callback, bool(monitor_all))
        touched = []

        def install(blk, prefix):
            for cname, child in blk._children.items():
                name = getattr(child, "name", None) or cname
                install(child, prefix + name + ".")
            label = prefix.rstrip(".") or (getattr(blk, "name", "") or
                                           type(blk).__name__)
            labels = getattr(blk, "_op_hook_labels", None)
            if labels is None:
                labels = blk._op_hook_labels = {}
            labels[entry[0]] = label
            cbs = getattr(blk, "_op_hook_cbs", None)
            if cbs is None:
                cbs = blk._op_hook_cbs = []
                orig = blk.forward

                def tap(*args, _orig=orig, _blk=blk, **kw):
                    from ..ndarray.ndarray import _is_tracer

                    def concrete(v):
                        # a hook registered BELOW a hybridized ancestor
                        # meets tracers during that ancestor's cache
                        # trace — skip those calls (register on the
                        # outermost block for every-call taps) rather
                        # than crash value-reading callbacks
                        return hasattr(v, "data") and not _is_tracer(
                            v.data)

                    # snapshot both together: detach() during the
                    # forward (capture-once callbacks) pops the label
                    hooks = list(_blk._op_hook_cbs)
                    lbls = dict(_blk._op_hook_labels)
                    for tok, cb, mon_all in hooks:
                        if mon_all:
                            for i, a in enumerate(args):
                                if concrete(a):
                                    cb(f"{lbls[tok]}_data{i}", a)
                    out = _orig(*args, **kw)
                    outs = out if isinstance(out, (list, tuple)) \
                        else [out]
                    for tok, cb, _mon_all in hooks:
                        for i, o in enumerate(outs):
                            if concrete(o):
                                suffix = "_output" if len(outs) == 1 \
                                    else f"_output{i}"
                                cb(f"{lbls[tok]}{suffix}", o)
                    return out

                blk._op_hook_fwd = (tap, orig)
                blk.forward = tap
            cbs.append(entry)
            # eager-path flag on EVERY block so nested hybridized
            # children also bypass their caches while tapped
            blk._op_hooks_active = getattr(blk, "_op_hooks_active",
                                           0) + 1
            touched.append(blk)

        install(self, "")

        class _OpHookHandle:
            def detach(self_inner):
                for blk in touched:
                    getattr(blk, "_op_hook_labels", {}).pop(entry[0], None)
                    cbs = getattr(blk, "_op_hook_cbs", None)
                    if cbs is not None and entry in cbs:
                        cbs.remove(entry)
                        blk._op_hooks_active = max(
                            0, getattr(blk, "_op_hooks_active", 1) - 1)
                        if not cbs:
                            tap, orig = blk._op_hook_fwd
                            if blk.forward is tap:
                                blk.forward = orig
                            del blk._op_hook_fwd
                            blk._op_hook_cbs = None
                touched.clear()

        return _OpHookHandle()

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer

        self.collect_params().initialize(init or initializer.Uniform(), ctx,
                                         verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def _collect_params_with_prefix(self, prefix=""):
        """Structure-based parameter names ("0.weight", "body.1.bias") so
        checkpoints are independent of name-counter state
        (reference: gluon/block.py _collect_params_with_prefix)."""
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        """Reference: gluon/block.py:416."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val.data() for key, val in params.items()
                    if val._ndarray is not None}
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Reference: gluon/block.py:472. Accepts both structure-based
        files (save_parameters) and arg:/aux:-prefixed export/Module
        checkpoints, matching the latter by full parameter name as the
        reference does."""
        self._load_loaded_parameters(nd.load(filename), filename,
                                     allow_missing, ignore_extra)

    def _load_loaded_parameters(self, loaded, filename,
                                allow_missing=False, ignore_extra=False):
        """Apply an already-deserialized ``nd.load`` dict (callers that
        inspected the file — SymbolBlock.imports — pass it through so
        big param files parse and device-upload once, not twice)."""
        if loaded and all(k.startswith(("arg:", "aux:")) for k in loaded):
            loaded = {k.split(":", 1)[1]: v for k, v in loaded.items()}
            params = dict(self.collect_params().items())
        else:
            params = self._collect_params_with_prefix()
            if loaded and not any(k in params for k in loaded):
                # reference-era zoo checkpoints use full parameter names
                # ("resnetv10_conv0_weight"), not structure paths
                by_name = dict(self.collect_params().items())
                if any(k in by_name for k in loaded):
                    params = by_name
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise IOError(f"Parameter '{name}' is missing in file "
                                  f"'{filename}'")
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise IOError(f"Parameter '{name}' loaded from file "
                                  f"'{filename}' is not present in Block")
                continue
            params[name]._load_init_from(loaded[name])

    save_params = save_parameters
    load_params = load_parameters

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a parameter/shape summary (reference: gluon/block.py
        summary)."""
        rows = []

        def walk(block, indent=0):
            n_params = sum(p.data().size for p in block._reg_params.values()
                           if p._ndarray is not None)
            rows.append("  " * indent + f"{type(block).__name__}"
                        f" ({block.name}): {n_params} params")
            for c in block._children.values():
                walk(c, indent + 1)

        walk(self)
        print("\n".join(rows))

    def __call__(self, *args, **kwargs):
        for hook in list(self._forward_pre_hooks):
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in list(self._forward_hooks):
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class CachedOp:
    """jit-compiled replay of a block's forward
    (reference: src/imperative/cached_op.{h,cc}; flags static_alloc etc. map
    to XLA donation/caching which jit already provides)."""

    def __init__(self, block, static_alloc=False, static_shape=False,
                 inline_limit=2, forward_bulk_size=None,
                 backward_bulk_size=None):
        from .. import env

        self._block = block
        self._param_list = None  # list[Parameter], fixed order
        self._out_treedefs = {}
        fn = self._pure
        # MXNET_BACKWARD_DO_MIRROR=1 (reference: src/nnvm/gradient.cc:275
        # mirror pass) — on TPU the memory-vs-compute lever is remat:
        # jax.checkpoint drops this op's forward activations and
        # recomputes them during backward
        if env.get_bool("MXNET_BACKWARD_DO_MIRROR"):
            fn = jax.checkpoint(fn, static_argnums=(0, 1))
        from ..utils import compile_cache as _cc

        self._jitted = _cc.counting_jit(fn, label="cached_op",
                                        static_argnums=(0, 1))

    def _ensure_params(self):
        if self._param_list is None:
            self._param_list = [p for _, p in
                                sorted(self._block.collect_params().items())]
        return self._param_list

    def _pure(self, amp_ver, train, param_vals, key, input_datas):
        # amp_ver is a static cache key only: a set_amp() bump forces a
        # retrace so the current AMP policy is baked into the new trace
        del amp_ver
        params = self._ensure_params()
        pnds = [p._ndarray for p in params]
        saved = [p._data for p in pnds]
        try:
            for p, v in zip(pnds, param_vals):
                p._data = v
            with autograd.pause(train_mode=train), mxrandom.key_provider(key):
                args = [NDArray(d) for d in input_datas]
                outs = self._block.forward(*args)
            flat, treedef = _flatten_outputs(outs)
            self._out_treedefs[bool(train)] = treedef
            mutated = {str(i): p._data for i, (p, v) in
                       enumerate(zip(pnds, param_vals)) if p._data is not v}
            return tuple(o.data for o in flat), mutated
        finally:
            for p, v in zip(pnds, saved):
                p._data = v

    def __call__(self, *args):
        params = self._ensure_params()
        # finish any deferred init with one throwaway eager pass
        if any(p._ndarray is None for p in params):
            with autograd.pause(train_mode=autograd.is_training()):
                self._block.forward(*args)
            self._param_list = None
            params = self._ensure_params()
        pnds = [p._ndarray for p in params]
        param_vals = [p._data for p in pnds]
        input_datas = [a.data for a in args]
        key = mxrandom.next_key()
        train = autograd.is_training()
        from ..ndarray import registry as _op_registry
        _amp_ver = _op_registry.amp_version()

        if autograd.is_recording():
            (out_datas, mutated), vjp_fn, = _vjp2(
                lambda pv, iv: self._jitted(_amp_ver, train, pv, key, iv),
                param_vals, input_datas)
            outs = [NDArray(d) for d in out_datas]

            def tape_vjp(cotangents, _vjp=vjp_fn, _n=len(out_datas)):
                cots = (cotangents,) if _n == 1 else tuple(cotangents)
                pv_grads, iv_grads = _vjp(cots)
                return list(pv_grads) + list(iv_grads)

            def tape_fun(*xs, _npv=len(pnds), _ver=_amp_ver,
                         _train=train, _key=key, _self=self):
                # primal for higher-order grads: replay the cached jit
                # (same RNG key -> same dropout mask as the recording)
                pv, iv = list(xs[:_npv]), list(xs[_npv:])
                out_d, _mut = _self._jitted(_ver, _train, pv, _key, iv)
                return tuple(out_d) if len(out_d) > 1 else out_d[0]

            autograd._record_op(tape_vjp, pnds + list(args), outs,
                                fun=tape_fun)
        else:
            out_datas, mutated = self._jitted(_amp_ver, train, param_vals,
                                              key, input_datas)
            outs = [NDArray(d) for d in out_datas]
        for i_str, val in mutated.items():
            pnds[int(i_str)]._data = val
        treedef = self._out_treedefs.get(bool(train))
        return _unflatten_outputs(outs, treedef)


def _vjp2(fn, pv, iv):
    out, vjp_fn, aux = jax.vjp(fn, pv, iv, has_aux=True)
    return (out, aux), vjp_fn


def _flatten_outputs(outs):
    if isinstance(outs, NDArray):
        return [outs], "single"
    if isinstance(outs, (list, tuple)):
        flat = []
        spec = []
        for o in outs:
            if isinstance(o, NDArray):
                flat.append(o)
                spec.append(1)
            else:
                sub = list(o)
                flat.extend(sub)
                spec.append(len(sub))
        return flat, ("seq", type(outs).__name__, spec)
    raise MXNetError(f"unsupported forward output type {type(outs)}")


def _unflatten_outputs(flat, treedef):
    if treedef == "single" or treedef is None:
        return flat[0] if len(flat) == 1 else tuple(flat)
    _, typ, spec = treedef
    out = []
    i = 0
    for n in spec:
        if n == 1:
            out.append(flat[i])
        else:
            out.append(tuple(flat[i:i + n]))
        i += n
    return tuple(out) if typ == "tuple" else out


class HybridBlock(Block):
    """Block that can be compiled (reference: gluon/block.py:838)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_op_args = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Reference: gluon/block.py:1039. Compilation == jax.jit."""
        self._active = active
        self._cached_op = None
        self._cached_op_args = dict(static_alloc=static_alloc,
                                    static_shape=static_shape, **kwargs)
        super().hybridize(active=False)  # only the outermost block compiles

    def _build_cache(self):
        self._cached_op = CachedOp(self, **self._cached_op_args)

    def _verify_on_hybridize(self, args):
        """MXNET_GRAPH_VERIFY-gated trace verification before the first
        CachedOp build: one paused eager forward is recorded
        (analysis.record_trace) and the dataflow passes — PRNG key
        reuse, use-after-donate, dead values — disposition per the mode.
        Runs once per cache build, never on the hot path."""
        from .. import analysis

        if analysis.verify_mode() == "off":
            return
        try:
            report = analysis.verify_block_call(
                self, args, subject=f"hybridize:{self.name}")
        except DeferredInitializationError:
            return  # params not yet shaped; CachedOp's own pass inits
        report.disposition()

    def infer_shape(self, *args):
        """Finish deferred param init from example inputs."""
        with autograd.pause():
            self.forward(*args)

    def cast(self, dtype):
        super().cast(dtype)
        self._cached_op = None

    def __call__(self, *args, **kwargs):
        # op hooks force the eager path so taps fire on EVERY call, not
        # just the trace (the reference's monitor-mode slowdown)
        if self._active and not kwargs \
                and not getattr(self, "_op_hooks_active", 0):
            if all(isinstance(a, NDArray) for a in args):
                if self._cached_op is None:
                    self._verify_on_hybridize(args)
                    self._build_cache()
                for hook in list(self._forward_pre_hooks):
                    hook(self, args)
                out = self._cached_op(*args)
                for hook in list(self._forward_hooks):
                    hook(self, args, out)
                return out
        return super().__call__(*args, **kwargs)

    def forward(self, x, *args):
        """Dispatch to hybrid_forward with params as kwargs
        (reference: gluon/block.py:1127). Symbol inputs trace the block
        through the sym namespace instead — the reference's F-dispatch
        (gluon/block.py:1146 _call_cached_op symbol branch) that powers
        ``export`` and ONNX."""
        from .. import symbol as _sym

        if isinstance(x, _sym.Symbol):
            params = {name: _sym.var(param.name)
                      for name, param in self._reg_params.items()}
            return self.hybrid_forward(_sym, x, *args, **params)
        params = {}
        for name, param in self._reg_params.items():
            try:
                params[name] = param.data()
            except DeferredInitializationError:
                self._infer_param_shapes(x, *args)
                params[name] = param.data()
        return self.hybrid_forward(nd, x, *args, **params)

    def _infer_param_shapes(self, x, *args):
        """Layers override `infer_param_shapes(x)`; generic fallback errors."""
        infer = getattr(self, "infer_param_shapes", None)
        if infer is None:
            raise DeferredInitializationError(
                f"{type(self).__name__} has deferred parameters and no "
                "shape-inference hook; call initialize() with known shapes")
        infer(x, *args)
        for p in self._reg_params.values():
            if p._ndarray is None and p._deferred_init is not None:
                p._finish_deferred_init()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0, input_names=("data",)):
        """Write ``path-symbol.json`` (reference-format nnvm JSON, via the
        F=sym trace) + ``path-{epoch:04d}.params`` (reference binary with
        arg:/aux: prefixes) — full parity with reference
        gluon/block.py:1077 export, loadable by SymbolBlock.imports, the
        Module API, and reference-era tooling. ``input_names`` sets the
        traced data-input variable names for multi-input blocks."""
        from .. import symbol as _sym
        from .. import ndarray as _nd

        out = self(*[_sym.var(n) for n in input_names])
        out.save(f"{path}-symbol.json")
        # aux states are what the graph says they are — the stat inputs
        # of batch_norm nodes — not "anything frozen": a weight with
        # grad_req='null' is still a graph argument
        aux_names = set()
        for s in out._walk():
            if s._op == "batch_norm" and len(s._inputs) >= 5:
                aux_names.update(i._name for i in s._inputs[3:5]
                                 if i._op is None)
        payload = {}
        for name, p in self.collect_params().items():
            tag = "aux" if name in aux_names else "arg"
            payload[f"{tag}:{name}"] = p.data()
        fname = f"{path}-{epoch:04d}.params"
        _nd.save(fname, payload)
        return fname

    def optimize_for(self, x, *args, backend=None, **kwargs):
        self.hybridize()
        return self(x, *args)


class SymbolBlock(HybridBlock):
    """Construct a block from a symbol graph (reference: gluon/block.py:1190).
    Implemented with the symbolic layer (mxnet_tpu.symbol)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        # every free variable of the graph that is not a declared input
        # becomes a Parameter (reference: gluon/block.py:1246 — arg/aux
        # inputs of the imported symbol turn into block params)
        input_names = {i.name for i in self._inputs}
        for s in outputs._walk():
            if s._op is None and not s._group \
                    and s._name not in input_names \
                    and s._name not in self._reg_params:
                self._reg_params[s._name] = self.params.get(
                    s._name, allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, ctx=None):
        """Reference: gluon/block.py SymbolBlock.imports. Serving loader
        glue: ``input_names=None`` infers the data inputs as the graph's
        free variables NOT present in ``param_file`` — the exported
        (symbol, params) pair fully determines which variables are fed
        per request, so a model server can load any export without
        out-of-band input metadata."""
        from .. import symbol as sym
        from .. import ndarray as _nd

        outputs = sym.load(symbol_file)
        loaded = _nd.load(param_file) if param_file is not None else None
        if input_names is None:
            if loaded is None:
                raise MXNetError(
                    "SymbolBlock.imports(input_names=None) needs "
                    "param_file to tell data inputs from parameters")
            saved = {k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                     else k for k in loaded}
            input_names = [n for n in outputs.list_arguments()
                           if n not in saved]
            if not input_names:
                raise MXNetError(
                    f"no free variables of {symbol_file!r} remain after "
                    f"binding {param_file!r}; pass input_names "
                    "explicitly")
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym.var(n) for n in input_names]
        ret = SymbolBlock(outputs, inputs)
        if loaded is not None:
            ret._load_loaded_parameters(loaded, param_file)
        return ret

    def _optimized_outputs(self):
        """MXNET_GRAPH_OPT-gated rewrite of the output graph, cached per
        (level, pipeline version, fusion salt, autotune salt) so
        toggling the fusion knobs — or a tuning record/trial landing —
        re-optimizes. Every forward — eager, under the hybridized
        CachedOp trace, and the serving session's ``_pure`` — evaluates
        this graph, so one rewrite covers all three."""
        from ..analysis import graph_opt

        level = graph_opt.opt_level()
        if level <= 0:
            return self._outputs
        from .. import autotune as _autotune
        from .. import kernels

        tag = (level, graph_opt.PIPELINE_VERSION,
               kernels.fusion_salt(),
               _autotune.autotune_salt())
        cached = getattr(self, "_graph_opt_cache", None)
        if cached is None or cached[0] != tag:
            opt, _ = graph_opt.optimize_symbol(
                self._outputs, level=level,
                subject=f"hybridize:{self.name or 'symbol_block'}")
            self._graph_opt_cache = (tag, opt)
            cached = self._graph_opt_cache
        return cached[1]

    def forward(self, *args):
        from .. import symbol as sym

        feed = {i.name: a for i, a in zip(self._inputs, args)}
        for name, p in self.collect_params().items():
            feed[name] = p.data()
        return self._optimized_outputs().eval_with(feed)
