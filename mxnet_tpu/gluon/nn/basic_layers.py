"""Basic Gluon layers.

TPU-native equivalent of python/mxnet/gluon/nn/basic_layers.py (reference:
Sequential, HybridSequential, Dense, Dropout, BatchNorm, InstanceNorm,
LayerNorm, GroupNorm, Embedding, Flatten, Lambda, HybridLambda).
"""
from __future__ import annotations

import numpy as onp

from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    """Reference: basic_layers.py Sequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                args = tuple(x[1:])
                x = x[0]
        if args:
            return (x,) + args
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Reference: basic_layers.py HybridSequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                args = tuple(x[1:])
                x = x[0]
        if args:
            return (x,) + args
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py Dense; op
    fully_connected → one MXU matmul)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation)
            else:
                self.act = None

    def infer_param_shapes(self, x, *args):
        in_units = int(onp.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.fully_connected(x, weight, bias, num_hidden=self._units,
                                flatten=self._flatten, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return f"Dense({shape[1] if shape and len(shape) > 1 else None} -> " \
               f"{self._units}, linear)"


class Activation(HybridBlock):
    """Reference: nn/activations.py Activation."""

    def __init__(self, activation, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._act_type = activation

    def hybrid_forward(self, F, x):
        return F.activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class Dropout(HybridBlock):
    """Reference: basic_layers.py Dropout."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.dropout(x, p=self._rate, axes=self._axes)
        return x


class BatchNorm(HybridBlock):
    """BatchNorm with running-stat state (reference: basic_layers.py
    BatchNorm + src/operator/nn/batch_norm.cc). Running stats are 'null'
    grad params mutated in-place during training — the CachedOp mutation
    channel carries them through jit."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        name = dtype if isinstance(dtype, str) else onp.dtype(dtype).name
        if name in ("float16", "bfloat16"):
            dtype = "float32"  # norm params/stats stay fp32 (AMP rule)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        training = autograd.is_training() and not self._use_global_stats
        if training:
            out, mean, var = F.batch_norm(
                x, gamma, beta, running_mean, running_var, eps=self._epsilon,
                momentum=self._momentum, fix_gamma=not self._scale,
                output_mean_var=True, axis=self._axis, use_batch_stats=True)
            m = self._momentum
            running_mean._data = (m * running_mean.data + (1 - m) * mean.data)
            running_var._data = (m * running_var.data + (1 - m) * var.data)
            return out
        return F.batch_norm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=True, axis=self._axis, use_batch_stats=False)


class InstanceNorm(HybridBlock):
    """Reference: basic_layers.py InstanceNorm."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.instance_norm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Reference: basic_layers.py LayerNorm."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.layer_norm(x, gamma, beta, axis=self._axis,
                            eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Reference: basic_layers.py GroupNorm."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            # gamma/beta are PER-GROUP (reference gluon
            # basic_layers.py:700 shape=(num_groups,))
            self.gamma = self.params.get("gamma", shape=(num_groups,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(num_groups,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_param_shapes(self, x, *args):
        self.gamma.shape = (self._num_groups,)
        self.beta.shape = (self._num_groups,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.group_norm(x, gamma, beta, num_groups=self._num_groups,
                            eps=self._epsilon)


class Embedding(HybridBlock):
    """Reference: basic_layers.py Embedding (op: indexing_op.h Embedding;
    XLA gather on TPU)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype)

    def hybrid_forward(self, F, x, weight):
        return F.embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Reference: basic_layers.py Flatten."""

    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Reference: basic_layers.py Lambda."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    """Reference: basic_layers.py HybridLambda."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)
