"""Convolution / pooling Gluon layers.

TPU-native equivalent of python/mxnet/gluon/nn/conv_layers.py (reference:
Conv1D-3D, Conv1D-3DTranspose, Max/Avg/GlobalPool1D-3D, ReflectionPad2D).
"""
from __future__ import annotations

import numpy as onp

from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    """Reference: conv_layers.py _Conv."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        nd_ = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._stride = _tuplize(strides, nd_)
        self._pad = _tuplize(padding, nd_)
        self._dilate = _tuplize(dilation, nd_)
        self._groups = groups
        self._layout = layout
        # channel-last layouts store the filter as (O, *k, I) — the
        # cuDNN-NHWC convention the reference uses on GPU (here: the
        # layout XLA:TPU prefers; see ops_nn._conv_dims)
        from ...ndarray.ops_nn import _CHANNEL_LAST

        self._channel_last = layout in _CHANNEL_LAST
        ic = in_channels // groups if in_channels else 0
        wshape = ((channels,) + kernel_size + (ic,)) if self._channel_last \
            else ((channels, ic) + kernel_size)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=wshape,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer)
            else:
                self.bias = None
            self.act = Activation(activation) if activation else None

    def infer_param_shapes(self, x, *args):
        if self._channel_last:
            in_c = x.shape[-1]
            self.weight.shape = (self._channels,) + self._kernel + \
                (in_c // self._groups,)
        else:
            in_c = x.shape[1]
            self.weight.shape = (self._channels, in_c // self._groups) + \
                self._kernel

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.convolution(x, weight, bias, kernel=self._kernel,
                            stride=self._stride, dilate=self._dilate,
                            pad=self._pad, num_filter=self._channels,
                            num_group=self._groups, no_bias=bias is None,
                            layout=self._layout)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"{type(self).__name__}({self._channels}, " \
               f"kernel_size={self._kernel}, stride={self._stride})"


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class _ConvTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides, padding, output_padding,
                 dilation, groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)
        if self._channel_last:
            raise ValueError(
                "transposed convolution supports channel-first layouts "
                "only (NCW/NCHW/NCDHW)")
        self._adj = _tuplize(output_padding, len(kernel_size))
        # deconv weight layout is (in, out/groups, *k), not (out, in/g, *k)
        self.weight._shape = (in_channels if in_channels else 0,
                              channels // groups) + tuple(kernel_size)

    def infer_param_shapes(self, x, *args):
        in_c = x.shape[1]
        # deconv weight layout: (in, out/groups, *kernel) (reference
        # deconvolution-inl.h)
        self.weight.shape = (in_c, self._channels // self._groups) + \
            self._kernel

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.deconvolution(x, weight, bias, kernel=self._kernel,
                              stride=self._stride, dilate=self._dilate,
                              pad=self._pad, adj=self._adj,
                              num_filter=self._channels,
                              num_group=self._groups, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 1), strides, padding,
                         output_padding, dilation, groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 2), strides, padding,
                         output_padding, dilation, groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 3), strides, padding,
                         output_padding, dilation, groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, layout=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kernel = pool_size
        self._stride = _tuplize(strides if strides is not None else pool_size,
                                len(pool_size)) if pool_size else None
        self._pad = _tuplize(padding, len(pool_size)) if pool_size else None
        self._ceil = ceil_mode
        self._global = global_pool
        self._type = pool_type
        self._count_include_pad = count_include_pad
        self._layout = layout

    def hybrid_forward(self, F, x):
        kw = {}
        if self._count_include_pad is not None:
            kw["count_include_pad"] = self._count_include_pad
        if self._layout is not None:
            kw["layout"] = self._layout
        return F.pooling(x, kernel=self._kernel, pool_type=self._type,
                         global_pool=self._global, stride=self._stride,
                         pad=self._pad,
                         pooling_convention="full" if self._ceil else "valid",
                         **kw)

    def __repr__(self):
        return f"{type(self).__name__}(size={self._kernel}, " \
               f"stride={self._stride}, padding={self._pad})"


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplize(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", layout=layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tuplize(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", layout=layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuplize(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", layout=layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplize(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", count_include_pad, layout=layout,
                         **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuplize(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", count_include_pad, layout=layout,
                         **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuplize(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", count_include_pad, layout=layout,
                         **kwargs)


class _GlobalPooling(_Pooling):
    def __init__(self, pool_type, layout=None, **kwargs):
        super().__init__((1,), None, 0, False, True, pool_type,
                         layout=layout, **kwargs)

    def __repr__(self):
        return type(self).__name__


class GlobalMaxPool1D(_GlobalPooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__("max", layout=layout, **kwargs)


class GlobalMaxPool2D(_GlobalPooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__("max", layout=layout, **kwargs)


class GlobalMaxPool3D(_GlobalPooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__("max", layout=layout, **kwargs)


class GlobalAvgPool1D(_GlobalPooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__("avg", layout=layout, **kwargs)


class GlobalAvgPool2D(_GlobalPooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__("avg", layout=layout, **kwargs)


class GlobalAvgPool3D(_GlobalPooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__("avg", layout=layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reference: conv_layers.py ReflectionPad2D."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
