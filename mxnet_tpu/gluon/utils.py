"""Gluon utils (reference: python/mxnet/gluon/utils.py)."""
from ..utils import (split_data, split_and_load, clip_global_norm, check_sha1,
                     download)

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "shape_is_known"]


def shape_is_known(shape):
    """Whether every dimension of `shape` is concrete (reference:
    gluon/utils.py shape_is_known — unknown is -1 under np semantics,
    0 under classic semantics)."""
    from ..util import is_np_shape

    if shape is None:
        return False
    unknown = -1 if is_np_shape() else 0
    if len(shape) == 0:
        return unknown == -1
    for d in shape:
        if d == unknown:
            return False
        assert d > unknown, \
            f"invalid dim size {d} in shape {tuple(shape)}"
    return True
