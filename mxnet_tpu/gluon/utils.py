"""Gluon utils (reference: python/mxnet/gluon/utils.py)."""
from ..utils import (split_data, split_and_load, clip_global_norm, check_sha1,
                     download)

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]
