"""Gluon Parameter / ParameterDict.

TPU-native equivalent of python/mxnet/gluon/parameter.py (reference:
Parameter:48 with deferred init, grad_req, lr_mult/wd_mult, per-ctx
replicas; ParameterDict; Constant). On TPU there is one logical copy of
each parameter — replication/sharding across chips is a jax.sharding
decision made by the parallel layer, not N explicit NDArray replicas as in
the reference's per-GPU `_ctx_list` model.
"""
from __future__ import annotations

import re
import warnings
from collections import OrderedDict

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import initializer
from ..context import current_context

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization (reference:
    gluon/parameter.py:40)."""


class Parameter:
    """A Block parameter (reference: gluon/parameter.py:48)."""

    def __init__(self, name, grad_req="write", shape=None, dtype=onp.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._ndarray = None
        self._deferred_init = None  # (init, ctx, default_init)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        self._grad_req = req
        if self._ndarray is not None:
            if req == "null":
                self._ndarray._ag_marked = False
                self._ndarray._grad = None
            else:
                self._attach_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # per-dim MERGE, 0 = unknown on EITHER side (reference
        # parameter.py get() inferred_shape): a sharing block created
        # with in_units=0 must not clobber the shared param's known dims
        assert len(self._shape) == len(new_shape) and all(
            i == 0 or j == 0 or i == j
            for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {self._shape} is incompatible with given shape " \
            f"{new_shape} for Parameter {self.name}"
        self._shape = tuple(j if i == 0 else i
                            for i, j in zip(new_shape, self._shape))

    def _shape_complete(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Reference: gluon/parameter.py initialize (deferred when shape
        unknown)."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._ndarray is not None and not force_reinit:
            return
        if not self._shape_complete():
            if not self.allow_deferred_init:
                raise ValueError(
                    f"Cannot initialize Parameter {self.name} because it has "
                    f"invalid shape {self._shape}")
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        arr = nd.zeros(self._shape, ctx=ctx if not isinstance(ctx, list) else
                       ctx[0], dtype=self.dtype)
        actual = init if init is not None else (self.init if self.init
                                                is not None else default_init)
        if isinstance(actual, str):
            actual = initializer.create(actual)
        actual(initializer.InitDesc(self.name), arr)
        self._ndarray = arr
        self._deferred_init = None
        if self._grad_req != "null":
            self._attach_grad()

    def _finish_deferred_init(self, inferred_shape=None):
        if inferred_shape is not None:
            self.shape = inferred_shape
        if self._deferred_init is None:
            raise DeferredInitializationError(
                f"Parameter {self.name} has not been initialized")
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _attach_grad(self):
        from .. import autograd

        g = nd.zeros(self._ndarray.shape, dtype=self._ndarray.data.dtype)
        autograd.mark_variables([self._ndarray], [g], self._grad_req)

    def _check_initialized(self):
        if self._ndarray is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet "
                    "because initialization was deferred. Actual "
                    "initialization happens during the first forward pass.")
            raise RuntimeError(
                f"Parameter {self.name} has not been initialized. You should "
                "initialize parameters with Block.initialize() first")

    def data(self, ctx=None):
        self._check_initialized()
        return self._ndarray

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad_req == "null" or self._ndarray._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter {self.name} "
                "because grad_req='null'")
        return self._ndarray._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._ndarray.context]

    def zero_grad(self):
        if self._ndarray is not None and self._ndarray._grad is not None:
            g = self._ndarray._grad
            g._data = nd.zeros(g.shape, dtype=g.data.dtype).data

    def reset_ctx(self, ctx):
        """Move the parameter's buffer (and grad) to another context
        (reference: parameter.py reset_ctx — raises for uninitialized
        parameters rather than silently placing them elsewhere later)."""
        import jax

        dev = getattr(ctx, "jax_device", ctx)
        if self._ndarray is None:
            raise ValueError(
                f"Cannot reset context for Parameter '{self.name}' "
                f"because it has not been initialized (deferred init "
                f"finishes on the first forward)")
        self._ndarray._data = jax.device_put(self._ndarray._data, dev)
        if self._ndarray._grad is not None:
            g = self._ndarray._grad
            g._data = jax.device_put(g._data, dev)

    def set_data(self, data):
        self.shape = data.shape
        if self._ndarray is None:
            if self._deferred_init is not None and self._shape_complete():
                self._finish_deferred_init()
            else:
                raise RuntimeError(
                    f"Parameter {self.name} has not been initialized")
        if isinstance(data, NDArray):
            self._ndarray._data = data.data.astype(self._ndarray.data.dtype)
        else:
            self._ndarray._data = nd.array(
                data, dtype=self._ndarray.data.dtype).data

    def cast(self, dtype):
        self.dtype = dtype
        if self._ndarray is not None:
            had_grad = self._ndarray._grad is not None
            self._ndarray = self._ndarray.astype(dtype)
            if had_grad and self._grad_req != "null":
                self._attach_grad()

    def var(self):
        from .. import symbol

        return symbol.var(self.name, shape=self.shape, dtype=self.dtype)


class Constant(Parameter):
    """Non-trainable constant (reference: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _Init(initializer.Initializer):
            def _init_weight(self2, _, arr):
                value.copyto(arr)

            _init_default = _init_weight

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_Init(),
                         differentiable=False)


class ParameterDict:
    """Dict of Parameters with prefix (reference: gluon/parameter.py
    ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __repr__(self):
        s = "\n".join(repr(p) for p in self._params.values())
        return f"ParameterDict {self._prefix}(\n{s}\n)"

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Create-or-retrieve (reference behavior incl. shared lookup)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    if k == "shape" and v is not None:
                        param.shape = v
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"Cannot update self with other because they "
                                 f"have different Parameters with the same "
                                 f"name '{k}'")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        """Reference: parameter.py ParameterDict.reset_ctx."""
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = sum(w.copy() for w in block) / len(block)
            if not param.name.startswith(strip_prefix):
                raise ValueError(f"Prefix '{strip_prefix}' is to be striped "
                                 f"before saving, but Parameter's name "
                                 f"'{param.name}' does not start with it")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = nd.load(filename)
        arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise IOError(f"Parameter {name} is missing in file "
                                  f"{filename}")
        for name in arg_dict:
            if name not in self._params:
                if not ignore_extra:
                    raise IOError(f"Parameter {name} loaded from file "
                                  f"{filename} is not present in this dict")
                continue
            self[name]._load_init_from(arg_dict[name])


def _load_init_from(self, data):
    if self._ndarray is None:
        self.shape = data.shape
        if self._deferred_init is not None:
            self._finish_deferred_init()
        else:
            self._finish_init(None, None, initializer.Uniform())
    self.set_data(data)


Parameter._load_init_from = _load_init_from
