"""mxnet_tpu: a TPU-native framework with MXNet's capability surface.

A from-scratch rebuild of Apache MXNet (reference: xiezhq-hermann/
incubator-mxnet @1.5, mounted read-only at /root/reference) designed
TPU-first on JAX/XLA/Pallas:

- `mx.nd` — imperative NDArray on jax.Array (async via XLA dispatch)
- `mx.autograd` — tape of jax.vjp closures
- `mx.gluon` — Block/HybridBlock; hybridize == jax.jit
- `mx.sym` + Module — symbolic graphs lowered to one XLA computation
- `mx.kvstore` / parallel — ICI/DCN collectives via jax.sharding Mesh
- optimizers/metrics/io/model_zoo — API parity with the reference

Conventional import: ``import mxnet_tpu as mx``.
"""
from __future__ import annotations

__version__ = "0.1.0"


def _distributed_is_initialized(jax):
    """``jax.distributed.is_initialized`` arrived after 0.4.x; there the
    tell is the private rendezvous client (initialized iff it exists)."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return fn()
    from jax._src import distributed as _dist

    return getattr(_dist.global_state, "client", None) is not None


def _maybe_init_distributed():
    """jax.distributed.initialize must run BEFORE anything touches the
    XLA backend, and importing this package touches it (PRNG state) —
    so when the launcher's rendezvous env is present (tools/launch.py
    MXNET_COORDINATOR), join the cluster here, first thing. The analog
    of the reference's implicit ps-lite bootstrap inside ``import
    mxnet`` when DMLC_PS_ROOT_URI is set."""
    import multiprocessing

    from . import env as _env

    coord = _env.get_str("MXNET_COORDINATOR")
    if not coord:
        return
    if multiprocessing.parent_process() is not None:
        # forkserver/spawn children (DataLoader workers, ...) inherit
        # the launcher env but must NOT re-join the cluster with the
        # parent's process_id — the coordinator would reject or hang
        return
    import jax

    if _distributed_is_initialized(jax):
        return  # an explicit launch.init() beat us
    # rendezvous failures propagate: a silently un-joined worker would
    # leave its peers hanging at their first collective — and a launch
    # env with the coordinator but not the rank vars is itself such a
    # failure (defaulting to rank 0 of 1 would fork the cluster)
    nproc = _env.get_str("MXNET_NUM_PROCESSES")
    pid = _env.get_str("MXNET_PROCESS_ID")
    if nproc is None or pid is None:
        raise RuntimeError(
            "MXNET_COORDINATOR is set but MXNET_NUM_PROCESSES/"
            "MXNET_PROCESS_ID are not — refusing to join the cluster "
            "with guessed rank (every worker would claim rank 0)")
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(pid))


def _maybe_enable_int64():
    """MXNET_INT64_TENSOR_SIZE=1 builds the reference with 64-bit tensor
    indexing and int64 arithmetic (reference: include/mxnet/libinfo.h:126,
    flag INT64_TENSOR_SIZE; nightly test_large_array.py). The TPU analog
    is JAX's x64 mode — it must be set before the first jax use."""
    from . import env as _env

    if (_env.get_str("MXNET_INT64_TENSOR_SIZE", "0") or "0").lower() in (
            "1", "true", "on"):
        import jax

        jax.config.update("jax_enable_x64", True)


_maybe_init_distributed()
_maybe_enable_int64()

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import random
from . import initializer
from . import initializer as init
from . import optimizer
from .optimizer import lr_scheduler
from . import metric
from . import io
from . import kvstore as kv
from . import kvstore
from . import gluon
from . import parallel
from . import pipeline  # noqa: F401
from . import resilience  # noqa: F401
from . import utils  # noqa: F401
from . import engine  # noqa: F401
from . import libinfo  # noqa: F401
from . import misc  # noqa: F401
from . import initialize as _initialize

_initialize.initialize()  # crash tracebacks + fork-safe engine (initialize.cc)
from . import symbol
from . import numpy as np
from . import numpy_extension as npx
from . import symbol as sym
from . import executor
from . import module
from . import module as mod
from . import model
from . import callback
from . import name  # noqa: F401
from . import attribute  # noqa: F401
from .attribute import AttrScope  # noqa: F401
from . import library  # noqa: F401
from . import recordio
from . import image  # noqa: F401
from . import rnn  # noqa: F401
from . import env  # noqa: F401
from . import tools  # noqa: F401
from . import contrib  # noqa: F401
from . import util  # noqa: F401
from . import log  # noqa: F401
from . import registry  # noqa: F401
from . import serving  # noqa: F401
from . import kvstore_server  # noqa: F401  (exits server-role processes)
from . import monitor as mon  # noqa: F401
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from .monitor import Monitor  # noqa: F401
from . import visualization  # noqa: F401
from .visualization import print_summary  # noqa: F401
from . import runtime  # noqa: F401
from . import analysis  # noqa: F401
from . import test_utils  # noqa: F401
from . import operator  # noqa: F401
from . import rtc  # noqa: F401

operator._install_nd_custom()

# reference alias: mx.viz.plot_network / print_summary
viz = visualization

# keep reference-style aliases
Context = Context

# env-knob wiring (mxnet_tpu.env KNOBS table): global seed + profiler
# autostart, applied once at import like the reference's engine init
if env.get_str("MXNET_SEED"):
    random.seed(env.get_int("MXNET_SEED", 0))
if env.get_bool("MXNET_PROFILER_AUTOSTART"):
    profiler.set_config(aggregate_stats=True)
    profiler.start()
env.check()
