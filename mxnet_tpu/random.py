"""Global RNG state + key provider.

TPU-native redesign of the reference RNG (reference:
include/mxnet/random_generator.h per-thread Philox states;
src/resource.cc:174-198 global/per-ctx seeding; python/mxnet/random.py).
JAX's counter-based PRNG replaces mutable generator state: a module-level
key is split per draw in eager mode, and a *key provider* stack lets traced
regions (CachedOp / hybridized blocks) thread an explicit key argument so
sampling stays pure under jit — the idiomatic TPU answer to MXNet's
stateful kParallelRandom resource.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "key_provider", "uniform", "normal", "randn",
           "randint", "exponential", "poisson", "gamma", "negative_binomial",
           "generalized_negative_binomial", "multinomial"]


# process-wide base seed: fresh per-thread states derive from it (with a
# thread-id fold-in so threads draw DIFFERENT streams), and mx.random.seed
# re-seeds it for threads created afterwards
_GLOBAL_SEED = [0]


class _RngState(threading.local):
    def __init__(self):
        base = jax.random.PRNGKey(_GLOBAL_SEED[0])
        if threading.current_thread() is not threading.main_thread():
            base = jax.random.fold_in(base, threading.get_ident()
                                      & 0x7FFFFFFF)
        self.key = base
        self.providers = []


_STATE = _RngState()


def seed(seed_state, ctx="all"):
    """Set the global seed (reference: mx.random.seed,
    python/mxnet/random.py; MXRandomSeed → ResourceManager SeedRandom
    src/resource.cc:174). Applies to this thread immediately and to
    threads created afterwards via the process-wide base seed."""
    _GLOBAL_SEED[0] = int(seed_state)
    _STATE.key = jax.random.PRNGKey(int(seed_state))
    _STATE.providers = []


def next_key():
    """Next PRNG key: from the innermost provider (traced region) or by
    splitting the global eager key."""
    if _STATE.providers:
        return _STATE.providers[-1]()
    _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


class key_logger:
    """Record the keys an op draws while tracing, delegating to whatever
    source is active (the global stream, or an enclosing provider such as
    CachedOp's key argument). The eager tape stores the logged keys so
    higher-order replay (autograd create_graph) re-derives gradients
    against the SAME random masks the forward used."""

    def __init__(self):
        self.keys = []
        self._installed = False

    def __enter__(self):
        if _STATE.providers:
            # an enclosing provider (CachedOp trace) owns key derivation;
            # its keys may be tracers — do not capture them on the eager
            # tape (CachedOp pins its own keys via tape_fun)
            return self

        def provider():
            _STATE.key, sub = jax.random.split(_STATE.key)
            self.keys.append(sub)
            return sub

        _STATE.providers.append(provider)
        self._installed = True
        return self

    def __exit__(self, *exc):
        if self._installed:
            _STATE.providers.pop()


class key_replayer:
    """Feed back keys captured by a key_logger, in order. Extra draws
    beyond the log fall through to the global stream (defensive — a
    primal fn draws a fixed number of keys per trace). With
    ``strict=True`` an extra draw raises instead: the compiled-dispatch
    cache pre-splits exactly the counted number of keys and passes them
    as executable arguments, so a fall-through split under jit would
    bake a concrete key into the compiled executable as a constant —
    silently reusing one mask forever. Raising turns that into a trace
    failure the dispatch layer catches and falls back from."""

    def __init__(self, keys, strict=False):
        self._keys = list(keys)
        self._i = 0
        self._strict = strict

    def _next(self):
        if self._i < len(self._keys):
            k = self._keys[self._i]
            self._i += 1
            return k
        if self._strict:
            raise RuntimeError(
                "op drew more PRNG keys than were pre-split for replay")
        _STATE.key, sub = jax.random.split(_STATE.key)
        return sub

    def __enter__(self):
        _STATE.providers.append(self._next)
        return self

    def __exit__(self, *exc):
        _STATE.providers.pop()


class key_provider:
    """Context manager installing a key source for traced regions.

    CachedOp tracing installs a provider that derives keys from an explicit
    key *argument* of the jitted function, so randomness is an input, not a
    baked-in constant.
    """

    def __init__(self, base_key):
        self._base = base_key
        self._count = 0

    def _next(self):
        k = jax.random.fold_in(self._base, self._count)
        self._count += 1
        return k

    def __enter__(self):
        _STATE.providers.append(self._next)
        return self

    def __exit__(self, *exc):
        _STATE.providers.pop()

    @property
    def used(self):
        return self._count > 0


# eager sampling API (mx.random.*) — thin over the registered ops
def _nd():
    from . import ndarray as nd

    return nd


def uniform(low=0, high=1, shape=(1,), dtype="float32", ctx=None, out=None):
    return _nd().random_uniform(low=low, high=high, shape=shape, dtype=dtype,
                                out=out)


def normal(loc=0, scale=1, shape=(1,), dtype="float32", ctx=None, out=None):
    return _nd().random_normal(loc=loc, scale=scale, shape=shape, dtype=dtype,
                               out=out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape or (1,), dtype, ctx)


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None):
    return _nd().random_randint(low=low, high=high, shape=shape, dtype=dtype,
                                out=out)


def exponential(scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    return _nd().random_exponential(lam=1.0 / scale, shape=shape, dtype=dtype,
                                    out=out)


def poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    return _nd().random_poisson(lam=lam, shape=shape, dtype=dtype, out=out)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    return _nd().random_gamma(alpha=alpha, beta=beta, shape=shape, dtype=dtype,
                              out=out)


def negative_binomial(k=1, p=1, shape=(1,), dtype="float32", ctx=None, out=None):
    return _nd().random_negative_binomial(k=k, p=p, shape=shape, dtype=dtype,
                                          out=out)


def generalized_negative_binomial(mu=1, alpha=1, shape=(1,), dtype="float32",
                                  ctx=None, out=None):
    return _nd().random_generalized_negative_binomial(
        mu=mu, alpha=alpha, shape=shape, dtype=dtype, out=out)


def multinomial(data, shape=(1,), get_prob=False, dtype="int32"):
    return _nd().sample_multinomial(data, shape=shape, get_prob=get_prob,
                                    dtype=dtype)
