"""Logging helpers (reference: python/mxnet/log.py).

`get_logger(name, filename, filemode, level)` returns a configured
logging.Logger with the reference's level-letter + timestamp format and
ANSI colors on TTYs.
"""
from __future__ import annotations

import logging
import sys

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL
NOTSET = logging.NOTSET

_LEVEL_CHAR = {DEBUG: "D", INFO: "I", WARNING: "W", ERROR: "E",
               CRITICAL: "C"}
_COLOR = {DEBUG: "\x1b[32m", INFO: "\x1b[32m", WARNING: "\x1b[33m",
          ERROR: "\x1b[31m", CRITICAL: "\x1b[35m"}

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING", "ERROR",
           "CRITICAL", "NOTSET"]


class _Formatter(logging.Formatter):
    """Level-letter + date format, colorized on TTY handlers
    (reference: log.py _Formatter)."""

    def __init__(self, colored=True):
        self._colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def format(self, record):
        char = _LEVEL_CHAR.get(record.levelno, "U")
        fmt = f"{char}%(asctime)s %(process)d %(pathname)s:%(lineno)d] " \
              f"%(message)s"
        if self._colored:
            color = _COLOR.get(record.levelno, "\x1b[34m")
            fmt = color + fmt[:1] + "\x1b[0m" + fmt[1:]
        self._style._fmt = fmt
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger (idempotent per name); file handlers are
    uncolored (reference: log.py get_logger)."""
    logger = logging.getLogger(name)
    # name=None is the ROOT logger: return it untouched (the reference
    # guards the same way) — attaching a handler there would reformat
    # every library's propagated records
    if name is None or getattr(logger, "_mx_init_done", False):
        return logger
    logger._mx_init_done = True
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        handler.setFormatter(_Formatter(colored=False))
    else:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_Formatter(
            colored=getattr(sys.stderr, "isatty", lambda: False)()))
    logger.addHandler(handler)
    logger.setLevel(level)
    return logger


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias kept for reference parity."""
    return get_logger(name, filename, filemode, level)
