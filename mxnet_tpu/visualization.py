"""Network visualization (reference: python/mxnet/visualization.py).

print_summary walks the Symbol DAG printing a per-layer table with output
shapes and parameter counts; plot_network emits graphviz when the library
is present (optional dependency, like the reference).
"""
from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Reference: visualization.py print_summary."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]

    shapes_by_name = {}
    if shape is not None:
        arg_names = symbol.list_arguments()
        arg_shapes, _, _ = symbol.infer_shape(**shape)
        shapes_by_name.update(dict(zip(arg_names, arg_shapes)))

    order = symbol._walk()
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields):
        line = ""
        for i, f in enumerate(fields):
            line = (line + str(f))[:positions[i] - 1]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display)
    print("=" * line_length)
    total_params = 0
    for node in order:
        if node._op is None and node._group is None:
            continue  # variables are listed as inputs of their consumers
        if node._group is not None:
            continue
        name = node.name or node._op
        inputs = [i.name or (i._op or "?") for i in (node._inputs or [])]
        nparams = 0
        for inp in (node._inputs or []):
            if inp._op is None and inp.name in shapes_by_name and \
                    inp.name != "data" and not inp.name.endswith("label"):
                s = shapes_by_name[inp.name]
                n = 1
                for d in s:
                    n *= d
                nparams += n
        total_params += nparams
        out_shape = ""
        if shape is not None:
            try:
                _, node_out, _ = node.infer_shape_partial(**shape)
                if node_out:
                    out_shape = "x".join(str(d) for d in node_out[0])
            except Exception:
                out_shape = "?"
        print_row([f"{name} ({node._op})", out_shape, nparams,
                   ", ".join(inputs[:3])])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Reference: visualization.py plot_network. Needs graphviz."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the graphviz python package") from e
    node_attrs = node_attrs or {}
    dot = Digraph(name=title)
    for node in symbol._walk():
        if node._group is not None:
            continue
        name = node.name or str(id(node))
        if node._op is None:
            if hide_weights and name != "data" and \
                    not name.endswith("label"):
                continue
            dot.node(name, label=name, shape="oval")
        else:
            dot.node(name, label=f"{name}\n{node._op}", shape="box",
                     **node_attrs)
        for inp in (node._inputs or []):
            iname = inp.name or str(id(inp))
            if hide_weights and inp._op is None and iname != "data" and \
                    not iname.endswith("label"):
                continue
            dot.edge(iname, name)
    return dot
