"""True asynchronous parameter server for multi-process ``dist_async``.

Reference: src/kvstore/kvstore_dist_server.h — in async mode each
worker's push is applied to the server copy INDIVIDUALLY the moment it
arrives; workers never wait on each other (bounded staleness), and a
worker's own pushes are visible to its next pull (read-your-writes via
the engine's per-key ordering).

TPU-native transport: the jax.distributed coordinator's key-value store
(the service every multi-host JAX job already runs) replaces ps-lite's
TCP vans. Wire protocol per key:

  mxps/val/<key>/<v>    canonical value at watermark v (npy bytes) —
                        the coordinator KV is WRITE-ONCE per key, so
                        each publish mints a fresh versioned key and
                        lazily deletes v-2 (readers retry the fetch)
  mxps/seq/<key>        atomic push counter (key_value_increment)
  mxps/push/<key>/<seq> one pending gradient, applied+deleted in order
  mxps/applied/<key>    applied watermark, advanced by increment —
                        pulls wait for their own seq

Rank 0 runs the applier thread (the "server"); its updater/optimizer is
the authoritative one, mirroring the reference where the optimizer is
shipped to the server (kvstore_dist_server ApplyUpdates). Because the
server rides on rank 0, workers must rendezvous (``kv.barrier()``)
before process teardown — the reference's ps-lite Finalize is likewise
a collective shutdown. Gradients ride
the coordinator channel, which is sized for control traffic — ideal for
the async protocol's semantics; bulk synchronous training should keep
using ``dist_sync`` (XLA collectives over ICI).
"""
from __future__ import annotations

import io
import threading
import time

import numpy as onp

from .base import MXNetError

_PREFIX = "mxps"

# Each dist_async KVStore created in a process gets its own namespace
# generation. SPMD programs create their stores in identical order on
# every process, so the per-process counter agrees globally — a second
# store no longer collides with the first one's write-once keys.
_GENERATION = [0]


def _log():
    import logging

    return logging.getLogger(__name__)


def _client():
    from jax._src import distributed

    c = distributed.global_state.client
    if c is None:
        raise MXNetError("dist_async parameter server needs "
                         "jax.distributed to be initialized")
    return c


def _ser(arr):
    buf = io.BytesIO()
    onp.save(buf, onp.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


def _deser(b):
    return onp.load(io.BytesIO(bytes(b)), allow_pickle=False)


def _serve_loop(ps_ref, stop):
    """Applier entry: holds only a WEAKREF to the server object, so a
    dropped kvstore (and its parameters) can be collected — the same
    no-pinning rule as the single-process applier in kvstore.py."""
    while not stop.is_set():
        ps = ps_ref()
        if ps is None:
            return
        busy = ps._sweep()
        del ps
        if not busy:
            time.sleep(0.005)


class AsyncParamServer:
    """Worker-side handle; rank 0 additionally runs the applier."""

    def __init__(self, rank, get_updater):
        import atexit
        import weakref

        _GENERATION[0] += 1
        self._prefix = f"{_PREFIX}{_GENERATION[0]}"
        self._c = _client()
        self._rank = rank
        self._get_updater = get_updater  # () -> updater|None, read at apply
        self._last_seq = {}  # key -> my highest pushed seq
        self._keys = set()
        self._server_vals = {}  # rank 0 only: canonical host copies
        self._stop = threading.Event()
        self._next_seq = {}   # rank 0: key -> next seq to apply
        self._gap_seen = {}   # rank 0: key -> first time the gap was seen
        from . import env as _env

        # how long rank 0 tolerates a missing gradient seq before
        # abandoning it (a crashed pusher must not stall the key forever;
        # a slow-but-alive worker needs the window to be tunable)
        self._gap_tolerance = _env.get_float(
            "MXNET_KVSTORE_GAP_TOLERANCE", 30.0)
        # transient coordinator-KV send failures retry with jittered
        # exponential backoff instead of failing the training step on
        # the first hiccup (reference: ps-lite van resend/timeouts);
        # the shared policy gives a clear terminal error after the
        # bounded attempts (docs/RESILIENCE.md)
        from .resilience import RetryPolicy

        self._retry = RetryPolicy(name="kvstore_ps send")
        self._published = {}  # rank 0: key -> watermark last published
        self._retire = {}     # rank 0: key -> version to delete next
        self._thread = None
        ref = weakref.ref(self)

        def _exit_flush():
            ps = ref()
            if ps is None:
                return
            try:  # tail pushes must land before the applier dies
                ps.flush(timeout_s=30.0)
            except Exception as e:
                _log().warning("dist_async exit flush failed: %s", e)
            ps.close()

        atexit.register(_exit_flush)
        if rank == 0:
            self._thread = threading.Thread(
                target=_serve_loop, args=(ref, self._stop), daemon=True)
            self._thread.start()

    # ---- worker API ------------------------------------------------------
    def init(self, key, value):
        key = str(key)
        self._keys.add(key)
        if self._rank == 0:
            val = onp.asarray(value.asnumpy(), dtype=onp.float32) \
                if hasattr(value, "asnumpy") else onp.asarray(value)
            self._server_vals[key] = val.copy()
            self._c.key_value_set_bytes(f"{self._prefix}/val/{key}/0",
                                        _ser(val))
        else:
            # wait for the server's initial value (blocking, like the
            # reference worker blocking on the server's init response)
            self._c.blocking_key_value_get_bytes(
                f"{self._prefix}/val/{key}/0", 120_000)

    def push(self, key, grad):
        """Non-blocking: enqueue and return (async semantics). Both
        coordinator-KV RPCs retry through the shared backoff policy —
        a transient rendezvous hiccup must not kill the step. NB the
        seq increment is claimed BEFORE the blob send; if every blob
        attempt fails, the claimed seq stays empty and the server's
        gap tolerance (MXNET_KVSTORE_GAP_TOLERANCE) reclaims it — the
        terminal RetryExhausted reaches the caller either way."""
        from .resilience import faults as _faults

        key = str(key)
        _faults.maybe_fail("kvstore_push")
        seq = self._retry.run(
            self._c.key_value_increment, f"{self._prefix}/seq/{key}", 1)
        blob = _ser(grad.asnumpy() if hasattr(grad, "asnumpy") else grad)
        self._retry.run(
            self._c.key_value_set_bytes,
            f"{self._prefix}/push/{key}/{seq:012d}", blob)
        self._last_seq[key] = seq

    def pull(self, key, timeout_s=120.0):
        """Read-your-writes: wait until the server has applied at least
        this worker's own last push for the key, then fetch the value
        published at (or after) that watermark."""
        key = str(key)
        want = self._last_seq.get(key, 0)
        deadline = time.monotonic() + timeout_s

        def applied_now():
            try:
                return int(self._c.key_value_try_get(
                    f"{self._prefix}/applied/{key}"))
            except Exception:
                return 0  # counter not created yet: nothing applied

        while True:
            applied = applied_now()
            if applied >= want:
                # fetch the version matching the watermark we read; the
                # server may already have published a NEWER version and
                # deleted this one — re-read the watermark and retry
                try:
                    blob = self._c.key_value_try_get_bytes(
                        f"{self._prefix}/val/{key}/{applied}")
                    return _deser(blob)
                except Exception:  # graft-lint: allow(L501)
                    pass  # version rotated away; loop re-reads
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"dist_async pull('{key}') timed out waiting for "
                    f"seq {want} (applied={applied}) — server down?")
            time.sleep(0.01)

    def flush(self, timeout_s=60.0):
        """Wait until every push from THIS worker has been applied."""
        for key in list(self._last_seq):
            self.pull(key, timeout_s)

    def close(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10)

    # ---- server (rank 0) -------------------------------------------------
    def _apply(self, key, grad):
        stored = self._server_vals.get(key)
        if stored is None:
            # rank 0's init always populates _server_vals, so a missing
            # key means a push raced ahead of init — fetch the latest
            # published version without blocking the applier loop
            v = self._published.get(key, 0)
            try:
                stored = _deser(self._c.key_value_try_get_bytes(
                    f"{self._prefix}/val/{key}/{v}"))
            except Exception:
                raise MXNetError(f"push to uninitialized key '{key}'")
            self._server_vals[key] = stored
        updater = self._get_updater()
        if updater is not None:
            from . import ndarray as nd
            from .kvstore import _key_to_int

            snd = nd.array(stored)
            updater(_key_to_int(key), nd.array(grad), snd)
            stored = snd.asnumpy()
        else:
            stored = stored + grad  # reference server default: sum
        self._server_vals[key] = stored
        return stored

    def _sweep(self):
        """ONE pass of the applier: apply pending pushes per key IN SEQ
        ORDER, publish new values and the applied watermark (the
        reference server's request-handling loop, poll-driven instead of
        RPC-driven). Returns whether any work was done."""
        if True:
            busy = False
            try:
                entries = self._c.key_value_dir_get_bytes(
                    f"{self._prefix}/push/")
            except Exception:
                entries = []
            by_key = {}
            for name, blob in entries:
                # name = mxps/push/<key>/<seq>
                parts = name.split("/")
                if len(parts) < 4:
                    continue
                by_key.setdefault(parts[2], []).append((parts[3], blob))
            for key, items in by_key.items():
                items.sort()  # zero-padded seq: lexicographic == numeric
                # apply STRICTLY CONSECUTIVE seqs: a pusher increments
                # the counter before its blob lands, so a visible seq
                # k+1 does not imply k arrived — applying k+1 first and
                # publishing applied=k+1 would let k's pusher pull a
                # value missing its own write (read-your-writes break)
                nxt = self._next_seq.get(key, 1)
                last = None
                for seqs, blob in items:
                    s = int(seqs)
                    if s < nxt:  # stale duplicate (already applied)
                        self._c.key_value_delete(
                            f"{self._prefix}/push/{key}/{seqs}")
                        continue
                    if s > nxt:
                        # gap: blob for `nxt` still in flight. Tolerate
                        # briefly (MXNET_KVSTORE_GAP_TOLERANCE seconds);
                        # a crashed pusher must not stall the key forever
                        # (reference: dead-worker timeouts)
                        first = self._gap_seen.setdefault(
                            key, time.monotonic())
                        if time.monotonic() - first > self._gap_tolerance:
                            _log().warning(
                                "dist_async server abandoning gradient "
                                "seq(s) %d..%d for key '%s' after %.0fs "
                                "gap tolerance; a slow worker's push is "
                                "lost (raise MXNET_KVSTORE_GAP_TOLERANCE "
                                "if workers stall transiently)",
                                nxt, s - 1, key, self._gap_tolerance)
                            self._gap_seen.pop(key, None)
                            nxt = s  # give up on the lost seq
                        else:
                            break
                    try:
                        self._apply(key, _deser(blob))
                    except Exception as e:
                        # a poisoned gradient must not kill the server;
                        # log and continue (reference does the same)
                        _log().warning(
                            "dist_async server dropped push seq %s for "
                            "key '%s': %s", seqs, key, e)
                    self._c.key_value_delete(
                        f"{self._prefix}/push/{key}/{seqs}")
                    self._gap_seen.pop(key, None)
                    last = s
                    nxt = s + 1
                    busy = True
                self._next_seq[key] = nxt
                if last is not None:
                    prev = self._published.get(key, 0)
                    # write-once store: publish under the NEW watermark,
                    # advance the counter by the delta, then retire the
                    # version before last (keeping one back version
                    # narrows the reader fetch race)
                    self._c.key_value_set_bytes(
                        f"{self._prefix}/val/{key}/{last}",
                        _ser(self._server_vals[key]))
                    self._c.key_value_increment(
                        f"{self._prefix}/applied/{key}", last - prev)
                    older = self._retire.pop(key, None)
                    if older is not None:
                        try:
                            self._c.key_value_delete(
                                f"{self._prefix}/val/{key}/{older}")
                        except Exception:  # graft-lint: allow(L501)
                            pass
                    self._retire[key] = prev
                    self._published[key] = last
            return busy
