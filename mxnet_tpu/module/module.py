"""Module: symbolic training over the jit Executor.

TPU-native equivalent of python/mxnet/module/module.py (reference:
Module:40-646 — bind/init_params/init_optimizer/forward/backward/update).
The reference splits the batch across a DataParallelExecutorGroup
(executor_group.py:144); on TPU the single Executor's computation is the
unit — data parallelism over chips is expressed by binding under a mesh
(mxnet_tpu.parallel), not by N executor replicas.
"""
from __future__ import annotations

import logging

import numpy as onp

from .. import ndarray as nd
from .. import optimizer as opt
from .. import kvstore as kvs
from ..base import MXNetError
from ..io import DataDesc
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._context = context
        self._group2ctxs = self._check_group2ctxs(group2ctxs, context)
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._arg_params = None
        self._aux_params = {}
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def _check_group2ctxs(group2ctxs, context):
        """Reference: graph_executor.cc:1915 places each ctx_group on its
        mapped device. One XLA computation cannot pin sub-graphs to
        arbitrary per-group devices — the TPU-native expression of model
        parallelism is mesh shardings (mxnet_tpu.parallel param_rules /
        SPMDTrainer). A trivial mapping (every group on the bind context)
        is honored; anything else fails LOUDLY instead of silently
        training on one device (reference c_api_executor.cc:314-338)."""
        if not group2ctxs:
            return None
        if isinstance(group2ctxs, dict):
            flat = {}
            for g, c in group2ctxs.items():
                cs = c if isinstance(c, (list, tuple)) else [c]
                flat[g] = list(cs)
            distinct = {str(c) for cs in flat.values() for c in cs}
            if context is None:
                from ..context import current_context

                base_ctxs = [current_context()]  # bind default
            elif isinstance(context, (list, tuple)):
                base_ctxs = list(context)
            else:
                base_ctxs = [context]
            base = {str(c) for c in base_ctxs}
            if distinct <= base and all(len(c) == 1 for c in flat.values()):
                return flat  # every group already on the bind context
        raise MXNetError(
            "group2ctxs placement is not supported by the single-"
            "computation Module: ctx_group placement maps to XLA mesh "
            "shardings on TPU — use mxnet_tpu.parallel.SPMDTrainer("
            "param_rules=...) (or bind every group to the module's own "
            "context). Refusing to silently ignore a model-parallel "
            "placement request.")

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        if self._exec.outputs:
            return [(n, tuple(o.shape)) for n, o in
                    zip(self.output_names, self._exec.outputs)]
        # before the first forward: simple_bind's inferred shapes
        # (reference keeps them from bind — executor.output_shapes)
        if not self._exec.output_shapes:
            raise MXNetError(
                "output shapes unavailable (bind-time inference was "
                "invalidated by reshape) — run forward() once first")
        return list(zip(self.output_names, self._exec.output_shapes))

    def _param_names(self):
        inputs = set(self._data_names) | set(self._label_names)
        return [n for n in self._symbol.list_arguments() if n not in inputs]

    # ---- bind ------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Reference: module.py:364 bind → simple_bind."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        self._label_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                              for d in (label_shapes or [])]
        shapes = {d.name: tuple(d.shape) for d in
                  self._data_shapes + self._label_shapes}
        self._exec = self._symbol.simple_bind(
            ctx=self._context, grad_req=grad_req if for_training else "null",
            **shapes)
        if isinstance(self._context, (list, tuple)) and \
                len(self._context) > 1:
            # multi-context bind = data parallelism: ONE computation with
            # batch inputs sharded over a 'dp' mesh of those devices
            # (reference: DataParallelExecutorGroup batch split,
            # executor_group.py:144; GSPMD inserts the grad all-reduce).
            # Fail HERE with a clear message, not deep inside the first
            # forward's device_put:
            devs = [c.jax_device for c in self._context]
            if len({id(d) for d in devs}) != len(devs):
                raise MXNetError(
                    f"multi-context bind needs DISTINCT devices; "
                    f"{self._context} map to {devs} (this host exposes "
                    f"fewer jax devices than contexts)")
            ndev = len(devs)
            for d in self._data_shapes + self._label_shapes:
                if d.shape and d.shape[0] % ndev:
                    raise MXNetError(
                        f"batch dim of '{d.name}' ({d.shape[0]}) must "
                        f"divide evenly over {ndev} contexts")
            self._exec.set_batch_names(
                [d.name for d in self._data_shapes + self._label_shapes])
        self.binded = True
        self.for_training = for_training

    # ---- params ----------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """Reference: module.py init_params."""
        assert self.binded
        if self.params_initialized and not force_init:
            return
        from .. import initializer as init_mod

        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        if arg_params is None and self._arg_params is not None:
            # params preloaded via Module.load / set_params-before-bind
            arg_params = self._arg_params
        for name in self._param_names():
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arg_params[name].copyto(arr)
            else:
                if arg_params is not None and not allow_missing and \
                        name not in arg_params:
                    raise RuntimeError(f"{name} is not presented")
                initializer(init_mod.InitDesc(name), arr)
        if aux_params is None and self._aux_params:
            aux_params = self._aux_params
        for name, arr in self._exec.aux_dict.items():
            # aux states keep their bind-time defaults (mean 0 / var 1)
            # unless a checkpoint provides them
            if aux_params and name in aux_params:
                aux_params[name].copyto(arr)
            elif aux_params and not allow_missing:
                raise RuntimeError(f"{name} is not presented")
        self.params_initialized = True

    def get_params(self):
        """Reference: module.py get_params."""
        assert self.binded and self.params_initialized
        arg_params = {n: self._exec.arg_dict[n].copy()
                      for n in self._param_names()}
        aux_params = {n: a.copy() for n, a in self._exec.aux_dict.items()}
        aux_params.update({k: v for k, v in self._aux_params.items()
                           if k not in aux_params})
        return arg_params, aux_params

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not self.binded:
            self._arg_params = arg_params
            self._aux_params = dict(aux_params or {})
            return
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    # ---- optimizer -------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Reference: module.py init_optimizer (kvstore wiring)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            idx2name = {i: n for i, n in enumerate(self._param_names())}
            # the reference normalizes by device batch size here
            # (reference: module.py init_optimizer rescale_grad=1/batch)
            if "rescale_grad" not in params and self._data_shapes:
                params["rescale_grad"] = 1.0 / self._data_shapes[0].shape[0]
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   **params)
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)
        if kvstore:
            self._kvstore = kvs.create(kvstore) \
                if isinstance(kvstore, str) else kvstore
        self.optimizer_initialized = True

    # ---- monitor ---------------------------------------------------------
    def install_monitor(self, mon):
        """Reference: module.py install_monitor → executor-group monitor
        callback. Accepts a Monitor (tic/toc protocol) or a bare
        ``callback(name, NDArray)``."""
        assert self.binded, "call bind() before install_monitor"
        if hasattr(mon, "install_to_executor"):
            mon.install_to_executor(self._exec)
        else:
            self._exec.set_monitor_callback(mon)

    # ---- step ------------------------------------------------------------
    def warmup(self, is_train=None):
        """Precompile this module's executor for its bound shapes (see
        ``Executor.warmup``) — no outputs, grads or aux states change."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._exec.warmup(is_train=is_train)

    def forward(self, data_batch, is_train=None):
        """Reference: module.py forward."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        """Reference: module.py backward."""
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads)

    def update(self):
        """Reference: module.py update → kvstore push/pull or updater."""
        assert self.optimizer_initialized
        for i, name in enumerate(self._param_names()):
            if name in self._fixed_param_names:
                continue
            w = self._exec.arg_dict[name]
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            self._updater(i, g, w)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict[n] for n in self._data_names
                if n in self._exec.grad_dict]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels or [])),
            dict(zip(self.output_names, self._exec.outputs)))

    # ---- checkpoint ------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Reference: module.py save_checkpoint → symbol json + params."""
        from ..model import save_checkpoint

        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Reference: module.py Module.load."""
        from .. import symbol as sym
        from ..model import load_checkpoint

        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._arg_params = arg_params
        mod._aux_params = dict(aux_params or {})
        mod._preloaded = (arg_params, aux_params)
        return mod
